"""TPU inference engine: continuous batching over compiled XLA steps.

This module replaces what the reference delegated to vLLM's
``AsyncLLMEngine`` (``llmq/workers/vllm_worker.py:104-123,183-195``): an
engine that coalesces many in-flight requests into device batches. The
TPU-native design differs from vLLM's CUDA core on purpose:

- **Fixed-shape compiled programs.** A batched prefill (bucketed
  whole-prompt by default, or fixed-[B, C] chunked against the paged
  cache via ``prefill_chunk_size``) and a ``max_num_seqs``-slot decode
  step. Requests churn; the compiled programs never change, so there is
  no recompilation in steady state.
- **Device-resident decode state + run-ahead pipeline.** The decode
  state (current tokens, context lengths, block tables, sampling state)
  lives on the device and is *updated by the compiled step itself*; the
  host dispatches step ``k`` while asynchronously fetching the sampled
  tokens of step ``k - runahead``. Steady-state decode therefore ships
  **zero** host→device bytes and never blocks on a device→host sync —
  critical when dispatch latency is high (remote TPU tunnels), and it
  removes host jitter everywhere else. Correctness pieces:
    * *Page lookahead*: KV pages are allocated at dispatch time for every
      position any in-flight step may write (`Scheduler.ensure_pages`),
      so the device block tables are never stale when a sequence crosses
      a page boundary.
    * *Device-side stopping*: per-slot limit/min/stop-token-id arrays let
      the compiled step deactivate finished slots itself, so EOS and
      max-token finishes need no host round-trip and no resync. Stop
      *strings* (host-only) mark the state dirty and force a resync.
    * *Deferred page frees*: pages of a finished sequence return to the
      allocator only after every dispatched step that might still write
      them has been processed (watermark on the dispatch counter).
- **Host scheduler, device compute.** `engine/scheduler.py` owns slots
  and KV pages in plain Python; resyncs rebuild the device state from it.
  Pages are refcounted: automatic prefix caching shares the leading full
  prompt pages of identical prefixes (blake2b chain match) and evicts
  lazily, and pool exhaustion triggers recompute preemption (re-queue,
  keep generated tokens, re-prefill later) rather than truncation.
- **SPMD via the mesh.** Weights/KV are sharded with ``NamedSharding``
  (`parallel/sharding.py`); GSPMD inserts the ICI collectives. The same
  engine runs single-chip or tensor-parallel across a slice unchanged.
- **Sampling on device.** Per-slot temperature/top-k/top-p/seed arrays;
  the model step and the sampler fuse into one executable.
- **Memory dtypes.** Weight-only int8 (``models/quant.py``) and an fp8
  (float8_e5m2) KV cache (``kv_dtype="fp8"``) are first-class: pools
  and params stay narrow in HBM, kernels convert on-chip, and the
  decode-kernel autotune calibrates at the production pool dtype.

An ``AsyncEngine`` wrapper runs the step loop on a dedicated thread and
bridges to asyncio futures, mirroring the AsyncLLMEngine surface the
reference consumed.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import queue
import threading
import time
from collections import deque
from concurrent.futures import Future
from functools import partial
from typing import Any, Deque, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
try:  # jax >= 0.5: Format pairs a per-device Layout with a sharding
    from jax.experimental.layout import Format, Layout
except ImportError:  # jax 0.4.x: same pair, pre-rename names
    from jax.experimental.layout import (
        DeviceLocalLayout as Layout,
        Layout as Format,
    )
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from llmq_tpu.core.faults import (
    FAULT_NUMERICAL,
    FAULT_OOM,
    DeviceFaultError,
    LogitGuardError,
    classify_failure,
)
from llmq_tpu.engine import sampling as sampling_mod
from llmq_tpu.engine import snapshot as snapshot_mod
from llmq_tpu.engine.prefix_store import PrefixStore
from llmq_tpu.engine.watchdog import NO_GUARD, DispatchWatchdog
from llmq_tpu.engine.snapshot import (
    KVRestore,
    RequestSnapshot,
    SnapshotCompatError,
)
from llmq_tpu.engine.sampling import (
    SamplingParams,
    make_base_key,
    request_tag,
    sample_tokens,
)
from llmq_tpu.engine.scheduler import (
    OutOfPages,
    Scheduler,
    SchedulerConfig,
    Sequence,
    mixed_token_budget,
)
from llmq_tpu.engine.tokenizer import Tokenizer
from llmq_tpu.models.config import ModelConfig
from llmq_tpu.models.transformer import Params, Transformer, make_kv_pages
from llmq_tpu.obs.metrics import (
    DEFAULT_BUCKETS,
    Gauge,
    Histogram,
    get_registry,
    to_ms,
)
from llmq_tpu.obs.trace import emit_trace_event
from llmq_tpu.ops import dispatch as _dispatch
from llmq_tpu.utils.host_mem import get_governor
from llmq_tpu.ops.attention import mixed_query_grid
from llmq_tpu.parallel import pipeline as pp_mod
from llmq_tpu.parallel.mesh import (
    DP_AXIS,
    SP_AXIS,
    TP_AXIS,
    make_mesh,
    mesh_pp,
)
from llmq_tpu.parallel.sharding import kv_page_pspec, param_shardings

logger = logging.getLogger(__name__)

#: ITL needs a finer low end than the default latency buckets: tokens of
#: one fused decode block reach the host in a burst, so sub-ms gaps are
#: the common case there.
ITL_BUCKETS: Tuple[float, ...] = (0.0001, 0.00025, 0.0005) + DEFAULT_BUCKETS

#: Cancellation requests for rids the engine doesn't hold (result already
#: emitted, or a disconnect raced the publish) age out of the sweep map
#: after this many seconds so it can never grow unboundedly.
_CANCEL_TTL_S = 5.0


@dataclasses.dataclass
class RequestOutput:
    """Final result of one generation request."""

    rid: str
    text: str
    token_ids: List[int]
    prompt_tokens: int
    completion_tokens: int
    finish_reason: str  # "stop" | "length"
    # Host-side monotonic lifecycle stamps (enqueued/admitted/
    # prefill_start/first_token/last_token/finished + preempt_count),
    # filled when the engine recorded them; workers project these onto
    # the request trace. None for sequences that predate instrumentation.
    timing: Optional[Dict[str, float]] = None
    # Prefill-only requests (finish_reason="prefill_done") carry the
    # prompt-KV snapshot here for the decode-pool handoff; None always
    # for normal completions.
    snapshot: Optional[Any] = None


@dataclasses.dataclass
class EngineConfig:
    max_num_seqs: int = 64
    max_model_len: int = 4096
    page_size: int = 32
    num_pages: Optional[int] = None  # None → size from device HBM
    hbm_utilization: float = 0.9
    # KV cache storage dtype. "fp8" here means float8_e5m2, stored
    # scale-free (no per-tensor scaling factors). Note the vLLM mapping:
    # vLLM's bare ``kv-cache-dtype=fp8`` is an alias for fp8_e4m3 (with
    # calibrated scales); our choice matches vLLM's *explicit*
    # ``fp8_e5m2`` option — e5m2 keeps bf16's exponent range so it needs
    # no scales, trading mantissa instead. Either way the win is the
    # same: half the KV bytes, so double the page pool in the same HBM
    # and half the decode-attention bandwidth. Compute stays f32 inside
    # the kernels (pages are converted on-chip); accepts a jnp dtype or
    # the strings "bf16"/"bfloat16"/"fp8"/"float8_e5m2"/"f32"/"float32".
    kv_dtype: Any = jnp.bfloat16
    min_prefill_bucket: int = 32
    max_prefill_batch: int = 4  # admitted seqs prefetched per iteration
    # Chunked prefill: process prompts in fixed-size chunks of this many
    # positions against the paged cache instead of whole-prompt buckets.
    # One compiled executable for ANY prompt length (no per-bucket
    # variants, ≤ chunk−1 positions of padding), and decode steps for the
    # already-running batch interleave between chunks, so a long prompt
    # no longer stalls every running slot for its whole prefill.
    # None → bucketed whole-prompt prefill (the default).
    prefill_chunk_size: Optional[int] = None
    # Automatic prefix caching (requires chunked prefill): requests that
    # share leading full prompt pages reuse the cached KV via refcounted
    # pages and prefill only the tail — e.g. a shared --map template or
    # system prompt is computed once, not per job.
    enable_prefix_caching: bool = False
    # Admission deferral waits for a full prefill chunk's worth of free
    # slots (throughput), but never keeps *deferring admissible work* for
    # longer than this (latency floor for trickle arrivals; the clock
    # starts at the first deferred step, not at enqueue).
    admit_max_wait_s: float = 0.5
    runahead: int = 8  # decode dispatches in flight ahead of result reads
    # Fused multi-step decode: one compiled XLA computation runs this
    # many decode iterations (a lax.scan over the single decode step —
    # attention, KV write, LM head, on-device sampling with the key
    # chain advanced on device) and returns a [K, S] token block, so the
    # host dispatches, snapshots, and fetches once per K tokens instead
    # of once per token. 1 = today's per-token dispatch (the exact same
    # executable as before). The trade: a sequence that finishes at
    # iteration j of a block still rides the remaining K-1 iterations as
    # an inactive row (its tokens are discarded on the host), so large K
    # wastes device work on short completions while shrinking host
    # overhead on long ones; bench.py measures 1/2/4 and keeps the best.
    decode_block: int = 1
    # Lossless speculative decoding: an on-device n-gram prompt-lookup
    # drafter proposes up to spec_tokens candidates per running row
    # (matching the row's recent spec_ngram-token suffix against its own
    # prompt+output history), and one fused verify dispatch scores all
    # spec_tokens+1 positions through the paged-attention path (q-len >
    # 1, exactly like chunked prefill). The longest candidate prefix the
    # model itself would have emitted is accepted — greedy requests are
    # bit-identical to spec_tokens=0, sampled requests keep the exact
    # output distribution via rejection sampling — so one dispatch can
    # emit up to spec_tokens+1 tokens. Rejected candidates' KV writes
    # are simply overwritten by the next step (pages are append-only;
    # per-row lengths rewind on device). 0 = off: the decode executable
    # is literally the non-speculative one. Composes with decode_block
    # (K verify iterations per dispatch).
    spec_tokens: int = 0
    # Draft-match n-gram length for prompt lookup. Longer = fewer but
    # more reliable matches.
    spec_ngram: int = 2
    # Per-slot device-side stop-token-id capacity. Grows automatically
    # (drain + resync + jit retrace at the wider shape) when a request's
    # stop set exceeds it, so min_tokens suppression always covers the
    # full set — no silent truncation.
    stop_id_capacity: int = 8
    # Tensor-parallel collective overlap: "on" replaces GSPMD's two
    # blocking per-layer all-reduces (after o_proj and down_proj) with
    # the chunked bidirectional ppermute rings in
    # ops/collective_matmul.py, so each ICI hop hides behind the next
    # chunk's matmul; "off" (default) traces the literal pre-existing
    # programs — the decode_block=1 / spec_tokens=0 precedent; "auto"
    # lets kernel_autotune A/B ring-vs-GSPMD per deployment.
    # LLMQ_TP_OVERLAP pins over this. Greedy outputs are token-identical
    # either way (the ring reduces in a different order, so float
    # bitstreams may differ at bf16).
    tp_overlap: str = "off"
    # Piggyback scheduling: "on" fuses one head-of-line prefill chunk
    # into each decode dispatch (a single executable runs the decode
    # batch plus up to chunk_size - decode_rows prefill positions for
    # one pending request through the shared paged-attention path), so
    # the MXU bubble left by the bandwidth-bound decode rows does the
    # prefill for free instead of alternating whole dispatches. Greedy
    # outputs are token-identical to "off" (the decode rows' math is
    # unchanged; the chunk rides as an extra row). Requires
    # prefill_chunk_size. LLMQ_MIXED_STEP pins over this.
    mixed_step: str = "off"
    # Pool-exhaustion preemption policy. "recompute" (default) drops the
    # victim's KV and re-prefills prompt+output on re-admission — cheap
    # bookkeeping, expensive re-compute. "swap" gathers the victim's KV
    # pages to host RAM as the deferred-release watermark passes and
    # scatters them back on re-admission, paying two PCIe copies instead
    # of a re-prefill. Greedy outputs are bit-identical either way (the
    # restored pages are the exact bytes the uninterrupted run would have
    # read). LLMQ_PREEMPT_MODE pins over this.
    preempt_mode: str = "recompute"
    # SLO priority classes: interactive sequences are admitted before
    # batch waiters and (priority_preempt) may swap/recompute-preempt
    # the youngest prefilled batch victim when they would otherwise
    # queue for a slot. Scheduling-order only — no sequence's own token
    # stream ever changes, so greedy outputs are token-identical with
    # the knob off. The admission order itself changes only once the
    # first interactive request arrives (lazily enabled, like
    # deadlines), so priority-free deployments are byte-identical.
    # LLMQ_PRIORITY_CLASSES pins over this.
    priority_classes: bool = True
    # Allow interactive admission to preempt a running batch sequence
    # (rides preempt_mode: swap gathers the victim's KV to host, else
    # recompute). LLMQ_PRIORITY_PREEMPT pins over this.
    priority_preempt: bool = True
    # Small-K interactive decode: when > 0 (and < decode_block) the
    # engine compiles a SECOND fused decode/verify executable at this
    # many scan iterations and dispatches it whenever an interactive
    # row is resident, so interactive ITL is bounded by the small K
    # while pure-batch steps keep the big fused decode_block. 0 = off
    # (every step uses decode_block — the pre-priority executables,
    # bit-for-bit). LLMQ_INTERACTIVE_DECODE_BLOCK pins over this.
    interactive_decode_block: int = 0
    # Host-RAM prefix cold tier (GiB of host blobs; 0 = off; requires
    # enable_prefix_caching): cache-registered pages evicted from the
    # device pool park in host RAM keyed by their chain digest, and a
    # later prompt walking the same chain gets them scattered back via
    # insert_kv_pages instead of re-prefilled. Blobs stay in the KV
    # pool's stored dtype, so a host-restored greedy continuation is
    # bit-identical to cold prefill. LLMQ_PREFIX_HOST_GB pins over this.
    prefix_host_gb: float = 0.0
    # Dispatch watchdog (0 = off): every device dispatch/fetch bracket
    # gets a monotonic deadline of max(watchdog_min_s, p99(kind) *
    # watchdog_mult) from the live per-kind dispatch histograms, and a
    # side thread detects (not interrupts — nothing can) a call that
    # overruns it. Off means no thread and no bracketing: the hot path
    # is byte-identical. LLMQ_WATCHDOG_MULT pins over this.
    watchdog_mult: float = 0.0
    # Deadline floor in seconds: protects against tripping on cold-start
    # compiles and empty histograms (a kind with no history uses the
    # floor alone). LLMQ_WATCHDOG_MIN_S pins over this.
    watchdog_min_s: float = 30.0
    # On-device logit guards: "on" folds cheap silent-corruption
    # reductions (any-NaN/Inf count, max |logit|, min row entropy) into
    # every decode/prefill/mixed/verify dispatch and ships the verdict
    # home alongside the sampled tokens — zero extra host syncs. A trip
    # raises the new ``numerical_fault`` class, and blame attribution
    # (re-run the suspects once on a rebuilt core) decides job-poison vs
    # device-fault. "off" (default) traces the literal pre-existing
    # programs. LLMQ_LOGIT_GUARD pins over this.
    logit_guard: str = "off"
    # Guard threshold: any finite logit magnitude above this trips the
    # "logit_max" check. 0 disables the magnitude check (the guard then
    # watches non-finites, plus entropy if enabled). Trace-time constant
    # — changing it retraces. LLMQ_GUARD_LOGIT_MAX pins over this.
    guard_logit_max: float = 0.0
    # Guard threshold: a masked row whose softmax entropy falls below
    # this many nats trips the "entropy_collapse" check (a corrupted
    # lm_head row or a stuck accumulator collapses the distribution to
    # near-determinism at positions where healthy models stay broad).
    # 0 disables. LLMQ_GUARD_ENTROPY_MIN pins over this.
    guard_entropy_min: float = 0.0
    # Background weight-audit cadence in seconds (0 = off): the engine
    # digests every parameter leaf on device at build, then re-digests
    # during idle steps at this cadence (and on demand after any guard
    # trip); a changed leaf means the HBM copy of the weights rotted,
    # distinguishing persistent corruption from a transient compute
    # error. LLMQ_WEIGHT_AUDIT_EVERY pins over this.
    weight_audit_every: float = 0.0
    # Canary self-test cadence in seconds (0 = off): a deterministic
    # golden prompt is generated greedily at engine build and replayed
    # during idle steps at this cadence (and after any suspicion);
    # anything but a bit-exact token match counts a canary failure,
    # which the worker advertises in its heartbeat so the janitor can
    # reclaim a chip that keeps failing. LLMQ_CANARY_EVERY pins over
    # this.
    canary_every: float = 0.0

    def __post_init__(self):
        self.decode_block = int(self.decode_block)
        if self.decode_block < 1:
            raise ValueError(
                f"decode_block={self.decode_block} (want >= 1)"
            )
        self.spec_tokens = int(self.spec_tokens)
        if self.spec_tokens < 0:
            raise ValueError(
                f"spec_tokens={self.spec_tokens} (want >= 0)"
            )
        self.spec_ngram = int(self.spec_ngram)
        if self.spec_ngram < 1:
            raise ValueError(
                f"spec_ngram={self.spec_ngram} (want >= 1)"
            )
        self.tp_overlap = str(self.tp_overlap).lower()
        if self.tp_overlap not in ("off", "on", "auto"):
            raise ValueError(
                f"tp_overlap={self.tp_overlap!r} (want off|on|auto)"
            )
        self.mixed_step = str(self.mixed_step).lower()
        if self.mixed_step not in ("off", "on"):
            raise ValueError(
                f"mixed_step={self.mixed_step!r} (want off|on)"
            )
        self.preempt_mode = str(self.preempt_mode).lower()
        if self.preempt_mode not in ("recompute", "swap"):
            raise ValueError(
                f"preempt_mode={self.preempt_mode!r} (want recompute|swap)"
            )
        self.interactive_decode_block = int(self.interactive_decode_block)
        if self.interactive_decode_block < 0:
            raise ValueError(
                f"interactive_decode_block={self.interactive_decode_block} "
                f"(want >= 0)"
            )
        self.prefix_host_gb = float(self.prefix_host_gb)
        if self.prefix_host_gb < 0:
            raise ValueError(
                f"prefix_host_gb={self.prefix_host_gb} (want >= 0)"
            )
        self.watchdog_mult = float(self.watchdog_mult)
        if self.watchdog_mult < 0:
            raise ValueError(
                f"watchdog_mult={self.watchdog_mult} (want >= 0)"
            )
        self.watchdog_min_s = float(self.watchdog_min_s)
        if self.watchdog_min_s <= 0:
            raise ValueError(
                f"watchdog_min_s={self.watchdog_min_s} (want > 0)"
            )
        self.logit_guard = str(self.logit_guard).lower()
        if self.logit_guard not in ("off", "on"):
            raise ValueError(
                f"logit_guard={self.logit_guard!r} (want off|on)"
            )
        self.guard_logit_max = float(self.guard_logit_max)
        if self.guard_logit_max < 0:
            raise ValueError(
                f"guard_logit_max={self.guard_logit_max} (want >= 0)"
            )
        self.guard_entropy_min = float(self.guard_entropy_min)
        if self.guard_entropy_min < 0:
            raise ValueError(
                f"guard_entropy_min={self.guard_entropy_min} (want >= 0)"
            )
        self.weight_audit_every = float(self.weight_audit_every)
        if self.weight_audit_every < 0:
            raise ValueError(
                f"weight_audit_every={self.weight_audit_every} (want >= 0)"
            )
        self.canary_every = float(self.canary_every)
        if self.canary_every < 0:
            raise ValueError(
                f"canary_every={self.canary_every} (want >= 0)"
            )
        if isinstance(self.kv_dtype, str):
            names = {
                "bf16": jnp.bfloat16,
                "bfloat16": jnp.bfloat16,
                "fp8": jnp.float8_e5m2,
                "fp8_e5m2": jnp.float8_e5m2,
                "float8_e5m2": jnp.float8_e5m2,
                "f32": jnp.float32,
                "float32": jnp.float32,
            }
            try:
                self.kv_dtype = names[self.kv_dtype.lower()]
            except KeyError:
                raise ValueError(
                    f"kv_dtype={self.kv_dtype!r} (want one of {sorted(names)})"
                ) from None


def _prefill_buckets(cfg: EngineConfig, sp: int = 1) -> List[int]:
    """Prompt buckets up to max_model_len: powers of two, plus quarter
    steps between octaves above 128. Pure doubling pads badly right
    where real prompts live — a 200-token prompt padded to 256 wastes
    28% of its prefill matmul FLOPs (prefill is compute-bound; padding
    is real work) — while quarter steps cap the waste at ~1/8th
    (200 -> 224). Below 128 the absolute waste is noise and extra
    compiled variants aren't worth it. Each bucket's prefill graph
    compiles lazily on first use, so unused buckets cost nothing.

    Every bucket is rounded up to a multiple of the sequence-parallel
    degree so ring attention (which shards the T axis over sp) applies
    to all of them — notably the top bucket, which is max_model_len
    itself and need not be on the ladder."""
    buckets = []
    b = cfg.min_prefill_bucket
    while b < cfg.max_model_len:
        buckets.append(b)
        if b >= 128:
            for quarter in (b + b // 4, b + b // 2, b + 3 * b // 4):
                if quarter < cfg.max_model_len:
                    buckets.append(quarter)
        b *= 2
    buckets.append(cfg.max_model_len)
    rounded = [-(-b // sp) * sp for b in buckets]
    return sorted(set(rounded))


# Pipeline entry: (dispatch index, kind "prefill"|"decode", device
#                  out-token array — or a (candidates, accept-counts)
#                  pair under speculative decoding —,
#                  [(row-in-out, Sequence), ...] snapshot,
#                  guard (stats, bad-rows) device pair or None)
_Pending = Tuple[int, str, Any, List[Tuple[int, Sequence]], Any]


class EngineCore:
    """Synchronous engine: owns device state and the step loop body."""

    def __init__(
        self,
        model_config: ModelConfig,
        params: Params,
        tokenizer: Tokenizer,
        *,
        mesh: Optional[Mesh] = None,
        engine_config: Optional[EngineConfig] = None,
    ) -> None:
        self.model_config = model_config
        self.tokenizer = tokenizer
        self.cfg = engine_config or EngineConfig()
        self.mesh = mesh if mesh is not None else make_mesh(tensor_parallel=1)
        # Pipeline parallelism: a (pp, dp, sp, tp) mesh is carved into pp
        # independent 3-axis stage submeshes; NOTHING is ever sharded
        # over pp. `self.mesh` is rebound to the LAST (head) stage's
        # submesh so every existing slot sharding, decode-state leaf and
        # sampler binding stays exactly where pp=1 put it — the head
        # stage owns decode state, sampling and the logits matmul, and
        # earlier stages only ever see (tokens, positions, block tables,
        # hidden states).
        self.full_mesh = self.mesh
        self.pp = mesh_pp(self.mesh)
        if self.pp > 1:
            if self.cfg.spec_tokens > 0:
                raise ValueError(
                    "spec_tokens > 0 with pp > 1 is not supported: the "
                    "draft/verify loop needs the full layer stack in one "
                    "executable (stage-split verify would ship hidden "
                    "states per candidate token)"
                )
            self._stage_meshes = pp_mod.stage_submeshes(self.mesh)
            self._stage_ranges = pp_mod.stage_layer_ranges(
                model_config.num_layers, self.pp
            )
            self.mesh = self._stage_meshes[-1]
        else:
            self._stage_meshes = [self.mesh]
            self._stage_ranges = [(0, model_config.num_layers)]
        # Resolved once, before any trace: the mode is a static field on
        # the frozen Transformer, so every jit variant (prefill buckets,
        # decode, verify, chunked prefill) sees the same choice and the
        # donation/sharding contracts are untouched.
        self.tp_overlap = _dispatch.resolve_tp_overlap(
            self.cfg.tp_overlap,
            self.mesh,
            hidden_size=model_config.hidden_size,
            intermediate_size=model_config.intermediate_size,
            max_seqs=self.cfg.max_num_seqs,
            logger=logger,
        )
        if self.pp > 1:
            # One Transformer + param subtree + sharding tree per stage.
            # The stage field confines the lax.scan to [lo, hi) layers
            # (local KV indices, global sliding-window policy); the
            # param/sharding trees are generic pytrees under a "stages"
            # key so tree-wide consumers (digest_params, the weight
            # audit) walk them unchanged.
            tied = "lm_head" not in params
            stage_models = []
            stage_trees = []
            stage_shardings = []
            for s, (lo, hi) in enumerate(self._stage_ranges):
                sub = pp_mod.slice_stage_params(
                    params,
                    lo,
                    hi,
                    num_layers=model_config.num_layers,
                    tied_embeddings=tied,
                )
                stage_models.append(
                    Transformer(
                        model_config,
                        mesh=self._stage_meshes[s],
                        tp_overlap=self.tp_overlap,
                        stage=(lo, hi),
                    )
                )
                sh = param_shardings(
                    self._stage_meshes[s], model_config, params=sub
                )
                stage_trees.append(
                    jax.tree.map(jax.device_put, sub, sh)
                )
                stage_shardings.append(sh)
            self._stage_models = stage_models
            self.model = stage_models[-1]
            self.params = {"stages": stage_trees}
            self._param_shardings = {"stages": stage_shardings}
        else:
            self._stage_models = None
            self.model = Transformer(
                model_config, mesh=self.mesh, tp_overlap=self.tp_overlap
            )
            self._param_shardings = param_shardings(
                self.mesh, model_config, params=params
            )
            self.params = jax.tree.map(
                jax.device_put, params, self._param_shardings
            )

        if self.cfg.enable_prefix_caching and not self.cfg.prefill_chunk_size:
            raise ValueError(
                "enable_prefix_caching requires prefill_chunk_size: only "
                "chunked prefill can start mid-prompt (the bucketed "
                "executables always compute positions 0..T)"
            )
        num_pages = self.cfg.num_pages or self._auto_num_pages()
        sched_cfg = SchedulerConfig(
            max_num_seqs=self.cfg.max_num_seqs,
            num_pages=num_pages,
            page_size=self.cfg.page_size,
            max_model_len=self.cfg.max_model_len,
            enable_prefix_caching=self.cfg.enable_prefix_caching,
        )
        self.scheduler = Scheduler(sched_cfg)
        self.scheduler.on_preempt = self._on_scheduler_preempt
        self._pages_per_seq = sched_cfg.pages_per_seq

        # Pin the KV pool to row-major layout at every jit boundary. Left
        # to itself XLA picks a different parameter layout than the Pallas
        # custom call's required default, then inserts FOUR full-pool
        # transpose copies per step in the entry computation (~12 ms/step
        # at 3B — measured round 2; dwarfs the attention kernel itself).
        # Under pp each stage owns its own pool holding just that stage's
        # [hi-lo] layer slab; every pool shares one page-index space (the
        # scheduler's), so block tables replicate across stages verbatim.
        self._kv_shardings = [
            NamedSharding(m, kv_page_pspec(model_config, m.shape[TP_AXIS]))
            for m in self._stage_meshes
        ]
        self._kv_formats = [
            Format(Layout(tuple(range(5))), sh) for sh in self._kv_shardings
        ]
        self._kv_sharding = self._kv_shardings[-1]
        self._kv_format = self._kv_formats[-1]
        if self.pp > 1:
            self.k_pages = []
            self.v_pages = []
            total_bytes = 0
            for s, (lo, hi) in enumerate(self._stage_ranges):
                k_s, v_s = make_kv_pages(
                    model_config,
                    num_pages,
                    self.cfg.page_size,
                    dtype=self.cfg.kv_dtype,
                    num_layers=hi - lo,
                )
                self.k_pages.append(jax.device_put(k_s, self._kv_formats[s]))
                self.v_pages.append(jax.device_put(v_s, self._kv_formats[s]))
                total_bytes += 2 * k_s.size * k_s.dtype.itemsize
            logger.info(
                "KV cache: %d pages x %d tokens (%.2f GiB total over %d "
                "pipeline stages), %d slots",
                num_pages,
                self.cfg.page_size,
                total_bytes / 2**30,
                self.pp,
                self.cfg.max_num_seqs,
            )
        else:
            k_pages, v_pages = make_kv_pages(
                model_config,
                num_pages,
                self.cfg.page_size,
                dtype=self.cfg.kv_dtype,
            )
            self.k_pages = jax.device_put(k_pages, self._kv_format)
            self.v_pages = jax.device_put(v_pages, self._kv_format)
            logger.info(
                "KV cache: %d pages x %d tokens (%.2f GiB total), %d slots",
                num_pages,
                self.cfg.page_size,
                2 * k_pages.size * k_pages.dtype.itemsize / 2**30,
                self.cfg.max_num_seqs,
            )

        # Slot-axis sharding: decode shards the batch over dp when it
        # divides evenly; otherwise slots are replicated (tp still shards
        # the model math). Production DP is per-process (reference parity).
        dp = self.mesh.shape[DP_AXIS]
        S = self.cfg.max_num_seqs
        slot_axis = DP_AXIS if dp > 1 and S % dp == 0 else None
        self._repl = NamedSharding(self.mesh, P())
        self._slot1 = NamedSharding(self.mesh, P(slot_axis))
        self._slot2 = NamedSharding(self.mesh, P(slot_axis, None))
        # Fused decode blocks stack K per-step token vectors: [K, S] with
        # the slot axis second, so each device still owns its dp shard.
        self._block1 = NamedSharding(self.mesh, P(None, slot_axis))
        # Speculative verify emits [K, S, Q] candidate tokens per
        # dispatch (Q = spec_tokens + 1); slot axis stays in the middle.
        self._spec_out = NamedSharding(self.mesh, P(None, slot_axis, None))

        self._eos_ids = set(model_config.eos_token_ids) | set(
            tokenizer.eos_token_ids
        )
        if (
            self.cfg.prefill_chunk_size
            and int(self.mesh.shape.get(SP_AXIS, 1)) > 1
        ):
            logger.warning(
                "prefill_chunk_size with sp>1: chunked prefill does not "
                "context-parallelize over the sp axis (each chunk computes "
                "replicated); use bucketed prefill for ring attention"
            )
        tp_size = int(self.mesh.shape.get(TP_AXIS, 1))
        if (
            os.environ.get("LLMQ_INT8_MATMUL", "").lower() == "pallas"
            and tp_size > 1
        ):
            # tp==1 scope (ops/pallas_matmul.py): demote to the XLA int8
            # path before this engine traces. Process-wide by design —
            # workers and bench build exactly one engine per process.
            # With tp_overlap=on the restriction only bites the
            # column-parallel GSPMD sites: the overlap rings' chunk
            # matmuls are plain local calls and keep the Pallas kernel
            # (ops/collective_matmul.py checks the env var directly).
            logger.warning(
                "LLMQ_INT8_MATMUL=pallas is single-chip-only (tp=%d mesh); "
                "using the XLA int8 matmul path for the rest of this "
                "process%s",
                tp_size,
                " (tp_overlap ring chunks keep the Pallas path)"
                if self.tp_overlap == "on"
                else "",
            )
            from llmq_tpu.models import quant as _qm

            _qm.disable_pallas_matmul(f"tp={tp_size} mesh")
        if (
            os.environ.get("LLMQ_INT4_MATMUL", "").lower() == "pallas"
            and tp_size > 1
        ):
            # Same single-chip scope as the int8 kernel above: the int4
            # Pallas matmul has no sharded lowering, so GSPMD call sites
            # demote to the dequant-einsum XLA path on tp>1 meshes while
            # the overlap rings' local chunk calls keep the kernel
            # (ops/collective_matmul.py checks LLMQ_INT4_MATMUL itself).
            logger.warning(
                "LLMQ_INT4_MATMUL=pallas is single-chip-only (tp=%d mesh); "
                "using the XLA int4 matmul path for the rest of this "
                "process%s",
                tp_size,
                " (tp_overlap ring chunks keep the Pallas path)"
                if self.tp_overlap == "on"
                else "",
            )
            from llmq_tpu.models import quant as _qm

            _qm.disable_pallas_matmul(f"tp={tp_size} mesh")
        # Piggyback scheduling: resolved once, before any trace, like
        # tp_overlap above. The env var pins over the config so bench /
        # A-B runs can flip it without threading a flag through workers.
        mixed = os.environ.get("LLMQ_MIXED_STEP", "").lower()
        if mixed in ("on", "off"):
            self.mixed_step = mixed
        else:
            self.mixed_step = self.cfg.mixed_step
        preempt = os.environ.get("LLMQ_PREEMPT_MODE", "").lower()
        if preempt in ("recompute", "swap"):
            self.preempt_mode = preempt
        else:
            self.preempt_mode = self.cfg.preempt_mode
        # SLO priority classes: env pins over config like the knobs
        # above. interactive_decode_block is a trace-time constant (it
        # sizes the second small-K executable), so it must resolve
        # before _build_steps below.
        pcls = os.environ.get("LLMQ_PRIORITY_CLASSES", "").lower()
        if pcls in ("0", "false", "no", "off"):
            self.priority_classes = False
        elif pcls in ("1", "true", "yes", "on"):
            self.priority_classes = True
        else:
            self.priority_classes = self.cfg.priority_classes
        ppre = os.environ.get("LLMQ_PRIORITY_PREEMPT", "").lower()
        if ppre in ("0", "false", "no", "off"):
            self.priority_preempt = False
        elif ppre in ("1", "true", "yes", "on"):
            self.priority_preempt = True
        else:
            self.priority_preempt = self.cfg.priority_preempt
        ik = self.cfg.interactive_decode_block
        env_ik = os.environ.get("LLMQ_INTERACTIVE_DECODE_BLOCK", "").strip()
        if env_ik:
            try:
                ik = int(env_ik)
            except ValueError:
                raise ValueError(
                    f"LLMQ_INTERACTIVE_DECODE_BLOCK={env_ik!r} is not an int"
                ) from None
        if ik < 0:
            raise ValueError(
                f"interactive_decode_block={ik} (want >= 0)"
            )
        self.interactive_decode_block = ik if self.priority_classes else 0
        # Host-RAM prefix cold tier: env pins over config like the knobs
        # above. Resolved before hook attachment so the scheduler's
        # eviction path demotes from the very first request.
        host_gb = self.cfg.prefix_host_gb
        env_gb = os.environ.get("LLMQ_PREFIX_HOST_GB", "").strip()
        if env_gb:
            try:
                host_gb = float(env_gb)
            except ValueError:
                raise ValueError(
                    f"LLMQ_PREFIX_HOST_GB={env_gb!r} is not a number"
                ) from None
        self.prefix_host_gb = host_gb
        self.prefix_store = None
        if host_gb > 0:
            if self.pp > 1:
                raise ValueError(
                    "prefix_host_gb > 0 with pp > 1 is not supported: the "
                    "host cold tier demotes single-pool pages; per-stage "
                    "pools need a per-stage demote path (device-level "
                    "prefix caching itself works — stage pools share the "
                    "page-index space)"
                )
            if not self.cfg.enable_prefix_caching:
                raise ValueError(
                    "prefix_host_gb > 0 requires enable_prefix_caching: "
                    "the host tier extends the device prefix cache (there "
                    "is nothing to demote without it)"
                )
            self.prefix_store = PrefixStore(
                int(host_gb * 2**30),
                page_size=self.cfg.page_size,
                model_sig=self._model_sig(),
            )
            self.scheduler.on_demote = self._demote_page
            self.scheduler.host_lookup = self._host_prefix_lookup
            logger.info(
                "prefix host tier: %.2f GiB budget (%d-token pages)",
                host_gb,
                self.cfg.page_size,
            )
        # Dispatch watchdog: env pins over config like the knobs above.
        # Resolved here; the monitor itself starts at the end of __init__
        # once the per-kind dispatch histograms (its deadline source)
        # exist.
        wd_mult = self.cfg.watchdog_mult
        env_mult = os.environ.get("LLMQ_WATCHDOG_MULT", "").strip()
        if env_mult:
            try:
                wd_mult = float(env_mult)
            except ValueError:
                raise ValueError(
                    f"LLMQ_WATCHDOG_MULT={env_mult!r} is not a number"
                ) from None
        wd_min = self.cfg.watchdog_min_s
        env_min = os.environ.get("LLMQ_WATCHDOG_MIN_S", "").strip()
        if env_min:
            try:
                wd_min = float(env_min)
            except ValueError:
                raise ValueError(
                    f"LLMQ_WATCHDOG_MIN_S={env_min!r} is not a number"
                ) from None
        self.watchdog_mult = wd_mult
        self.watchdog_min_s = wd_min
        self.watchdog: Optional[DispatchWatchdog] = None
        # Numerics-integrity knobs: env pins over config like the knobs
        # above. The guard flag and its thresholds are resolved before
        # _build_steps because they are trace-time constants — "off"
        # traces the literal pre-existing programs.
        guard = os.environ.get("LLMQ_LOGIT_GUARD", "").lower()
        if guard in ("on", "off"):
            self.logit_guard = guard
        else:
            self.logit_guard = self.cfg.logit_guard
        if self.logit_guard == "on" and self.pp > 1:
            raise ValueError(
                "logit_guard=on with pp > 1 is not supported: the guard "
                "widens every jit's output tuple, and the pp drivers "
                "re-dispatch those tuples across stage boundaries"
            )
        self.guard_logit_max = self.cfg.guard_logit_max
        env_gmax = os.environ.get("LLMQ_GUARD_LOGIT_MAX", "").strip()
        if env_gmax:
            try:
                self.guard_logit_max = float(env_gmax)
            except ValueError:
                raise ValueError(
                    f"LLMQ_GUARD_LOGIT_MAX={env_gmax!r} is not a number"
                ) from None
        self.guard_entropy_min = self.cfg.guard_entropy_min
        env_gent = os.environ.get("LLMQ_GUARD_ENTROPY_MIN", "").strip()
        if env_gent:
            try:
                self.guard_entropy_min = float(env_gent)
            except ValueError:
                raise ValueError(
                    f"LLMQ_GUARD_ENTROPY_MIN={env_gent!r} is not a number"
                ) from None
        self.weight_audit_every = self.cfg.weight_audit_every
        env_audit = os.environ.get("LLMQ_WEIGHT_AUDIT_EVERY", "").strip()
        if env_audit:
            try:
                self.weight_audit_every = float(env_audit)
            except ValueError:
                raise ValueError(
                    f"LLMQ_WEIGHT_AUDIT_EVERY={env_audit!r} is not a number"
                ) from None
        self.canary_every = self.cfg.canary_every
        env_canary = os.environ.get("LLMQ_CANARY_EVERY", "").strip()
        if env_canary:
            try:
                self.canary_every = float(env_canary)
            except ValueError:
                raise ValueError(
                    f"LLMQ_CANARY_EVERY={env_canary!r} is not a number"
                ) from None
        if self.mixed_step == "on" and not self.cfg.prefill_chunk_size:
            raise ValueError(
                "mixed_step=on requires prefill_chunk_size: the fused "
                "dispatch piggybacks a prefill *chunk* onto the decode "
                "batch (bucketed whole-prompt prefill has no chunks)"
            )
        # LLMQ_PP_WIRE=1 routes every stage-boundary hidden-state handoff
        # through the snapshot wire codec (serialize → frame → decode →
        # device_put) instead of a direct device_put. Lossless — the
        # codec round-trips raw bytes — so greedy parity holds; it is the
        # single-process stand-in for the inter-host tcp:// hop and keeps
        # the wire format honest (the same frames ship over DCN when
        # stages live on different hosts).
        self.pp_wire = os.environ.get("LLMQ_PP_WIRE", "0") == "1"
        self._buckets = _prefill_buckets(
            self.cfg, sp=int(self.mesh.shape.get(SP_AXIS, 1))
        )
        # Small-K interactive decode executables; _make_jits populates
        # this when interactive_decode_block is on (pp=1 only — the pp
        # drivers keep the single big-K pipeline).
        self._decode_jits_small: Optional[Dict[str, Any]] = None
        self._build_steps()

        # Host-side mirrors of the device decode state, rebuilt wholesale
        # at every resync (resyncs are rare; steady-state decode ships
        # nothing host→device).
        self._stop_capacity = self.cfg.stop_id_capacity
        E = self._stop_capacity
        # Construction-time shape probe (no dispatch in flight yet).
        key_shape = np.asarray(make_base_key(0, 0)).shape  # llmq: ignore[unguarded-device-fetch]
        self._h_tokens = np.zeros((S,), np.int32)
        self._h_ctx = np.zeros((S,), np.int32)
        self._h_bt = np.zeros((S, self._pages_per_seq), np.int32)
        self._h_active = np.zeros((S,), bool)
        self._h_temp = np.zeros((S,), np.float32)
        self._h_topk = np.zeros((S,), np.int32)
        self._h_topp = np.ones((S,), np.float32)
        self._h_keys = np.zeros((S, *key_shape), np.uint32)
        self._h_steps = np.zeros((S,), np.int32)
        self._h_limits = np.zeros((S,), np.int32)
        self._h_mins = np.zeros((S,), np.int32)
        self._h_stopids = np.full((S, E), -1, np.int32)
        # Speculative decoding only: per-slot prompt+output token history
        # ([S, max_model_len], the drafter's lookup corpus). Appended as
        # the 13th decode-state leaf so drafting happens on device — the
        # run-ahead pipeline still ships zero bytes host→device in steady
        # state.
        self._h_history = (
            np.zeros((S, self.cfg.max_model_len), np.int32)
            if self.cfg.spec_tokens > 0
            else None
        )

        # Run-ahead pipeline state.
        self._pending: Deque[_Pending] = deque()
        self._pending_decodes = 0  # decode entries within _pending
        self._defer_since: Optional[float] = None  # admission-deferral start
        self._deferred_pages: List[Tuple[int, List[int], int]] = []
        # Swap-to-host captures awaiting their deferred-release watermark:
        # (dispatch_idx, seq, pages, kv_valid, epoch-at-preemption). Each
        # rides the same watermark as its _deferred_pages entry and is
        # gathered to host BEFORE those pages return to the allocator.
        self._pending_swaps: List[Tuple[int, Sequence, List[int], int, int]] = []
        self._dispatch_idx = 0
        self._processed_idx = 0
        self._dirty = True
        self._mode = "greedy"
        self._dev_state: Optional[tuple] = None
        # Chaos/test hook: called with the dispatch kind ("prefill",
        # "mixed", "decode_block", "verify") after every device dispatch
        # is recorded. Runs on the engine thread; must be cheap.
        self.on_dispatch: Optional[Any] = None

        # Counters for stats/heartbeats.
        self.total_prompt_tokens = 0
        self.total_generated_tokens = 0
        self.decode_steps = 0  # device decode iterations (K per dispatch)
        self.decode_dispatches = 0  # host round trips for those iterations
        self.spec_proposed = 0  # draft tokens offered for verification
        self.spec_accepted = 0  # draft tokens the model confirmed
        self.prefills = 0
        self.mixed_steps = 0  # fused decode+prefill dispatches
        self.mixed_prefill_tokens = 0  # prompt positions piggybacked
        self.swap_preempts = 0  # preemptions whose KV was swapped to host
        self.kv_restores = 0  # admissions restored from host KV pages
        self.snapshots_extracted = 0
        self.snapshots_inserted = 0
        self.prefill_done = 0  # prefill-only requests finished at the boundary
        # rid → RequestSnapshot taken at the prefill boundary, popped by
        # _output_for when the finished RequestOutput is built. Transient:
        # entries live only between _append_and_check and the drain of the
        # same step's finished list.
        self._prefill_snapshots: Dict[str, RequestSnapshot] = {}
        self.prefill_tokens = 0  # prompt positions actually computed
        self.prefix_demotes = 0  # pages parked in the host tier on evict
        self.prefix_promotes = 0  # pages restored from the host tier
        self.prefix_chunks_exported = 0  # pages serialized for peers
        self.prefix_chunks_ingested = 0  # shipped pages accepted
        self.deadline_expirations = 0  # sequences expired by the sweep
        # SLO priority plane. _priority_enabled flips at the first
        # interactive request (like _deadlines_enabled): a fleet that
        # never sets Job.priority keeps the exact pre-priority admission
        # order AND byte-identical stats payloads.
        self._priority_enabled = False
        self.priority_preemptions = 0  # batch victims evicted for interactive
        # Per-class finish accounting for goodput: requests that finished
        # cleanly ("stop"/"length"/EOS) vs shed/expired/cancelled ones.
        self.class_finished = {"interactive": 0, "batch": 0}
        self.class_tokens = {"interactive": 0, "batch": 0}
        # Client-disconnect cancellation: rid → monotonic enqueue time.
        # Swept between steps; unknown rids (result already out, or a
        # request this engine never saw) age out after _CANCEL_TTL_S.
        self._cancel_rids: Dict[str, float] = {}
        self.cancellations = 0  # sequences finished by the cancel sweep
        # Per-token host callback (streaming): called on the engine
        # thread as (seq, token) for every token that SURVIVES the stop
        # check (popped stop tokens never stream). Must be cheap.
        self.on_token: Optional[Any] = None
        self.swap_refused = 0  # captures the host-memory governor declined
        self.hbm_oom_events = 0  # allocation faults the ladder absorbed
        # Numerics-integrity counters (superset-only in stats: all stay
        # at zero — and their stats keys absent — with the knobs off).
        # Pipeline-parallel boundary accounting (pp > 1 only; superset-
        # only keys in stats). One "transfer" is one stage→stage hidden-
        # state handoff; bytes count the [rows, T, H] activation payload.
        self.pp_boundary_bytes = 0
        self.pp_boundary_transfers = 0
        self.guard_trips = 0  # dispatches whose on-device guard fired
        self.weight_audits = 0  # background/on-demand digest sweeps run
        self.weight_audit_mismatches = 0  # leaves whose HBM digest changed
        self.kv_spot_checks = 0  # KV page read-stability samples
        self.canary_runs = 0  # golden-prompt replays
        self.canary_failures = 0  # replays that were not bit-exact
        # Leaf paths from the most recent failed audit (bounded: replaced
        # wholesale per audit, never appended across audits).
        self._last_audit_mismatch: List[str] = []
        self._weight_baseline: Optional[Dict[str, Tuple[int, int]]] = None
        self._canary_golden: Optional[List[int]] = None
        self._next_weight_audit = 0.0
        self._next_canary = 0.0
        # HBM-OOM degradation ladder position (monotonic per engine: a
        # pool that OOMed once stays degraded) and the rungs taken, in
        # order, for stats/probes.
        self._oom_rung = 0
        # Bounded by construction: the rung counter is monotonic 0→3, so
        # at most three entries are ever appended per engine lifetime.
        self._oom_ladder_log: List[str] = []  # llmq: ignore[unbounded-host-buffer]
        # Flipped by the first deadline-carrying request so the per-step
        # sweep costs nothing on deadline-free deployments.
        self._deadlines_enabled = False
        self._started_at = time.monotonic()

        # Unified host-memory governor: the prefix cold tier and the
        # swap-restore blobs report into the shared budget (registration
        # only when a budget is configured — default engines touch
        # nothing). Names are per-instance so test processes running
        # several engines don't shadow each other's gauges.
        gov = get_governor()
        if gov.enabled:
            tag = f"engine-{id(self):x}"
            gov.register(f"swap:{tag}", self._swap_restore_bytes)
            if self.prefix_store is not None:
                gov.register(
                    f"prefix:{tag}",
                    lambda: self.prefix_store.occupancy_bytes,
                    evict_fn=self._evict_prefix_bytes,
                )

        # Observability: host-side only — a histogram record is a bucket
        # increment, never inside jitted code. Per-engine instances
        # (not registry get-or-create) so stats() percentiles never mix
        # across the many engines a test process builds; `register`
        # replaces same-named series, so the latest engine owns the
        # exported ones (one engine per worker process in production).
        self.ttft_hist = Histogram(
            "llmq_ttft_seconds", "Enqueue-to-first-token latency"
        )
        self.itl_hist = Histogram(
            "llmq_itl_seconds",
            "Inter-token latency at the host boundary",
            buckets=ITL_BUCKETS,
        )
        # Per-class SLO latency series: interactive requests observe into
        # BOTH the all-class hists above and these labeled ones, so the
        # unlabeled series keeps its pre-priority meaning. Batch gets no
        # extra series (it IS the unlabeled series minus interactive, and
        # a priority-free fleet's export stays identical).
        self.ttft_hist_interactive = Histogram(
            "llmq_ttft_seconds",
            "Enqueue-to-first-token latency (interactive class)",
            labels={"class": "interactive"},
        )
        self.itl_hist_interactive = Histogram(
            "llmq_itl_seconds",
            "Inter-token latency at the host boundary (interactive class)",
            buckets=ITL_BUCKETS,
            labels={"class": "interactive"},
        )
        # Keyed by dispatch kind ("prefill"/"decode"/"mixed") — a fixed
        # set; the ring deques themselves carry maxlen.
        self._dispatch_rings: Dict[str, Deque[float]] = {}  # llmq: ignore[unbounded-host-buffer]
        self._dispatch_hists: Dict[str, Histogram] = {}  # llmq: ignore[unbounded-host-buffer]
        reg = get_registry()
        for metric in (
            self.ttft_hist,
            self.itl_hist,
            self.ttft_hist_interactive,
            self.itl_hist_interactive,
            self.scheduler.queue_wait_hist,
            self.scheduler.preempt_delay_hist,
            Gauge(
                "llmq_engine_tokens_per_sec",
                "Generated tokens per second since engine start",
                fn=lambda: self.total_generated_tokens
                / max(1e-9, time.monotonic() - self._started_at),
            ),
            Gauge(
                "llmq_engine_kv_page_utilization",
                "Fraction of the KV page pool in use",
                fn=lambda: (
                    (self.scheduler.config.num_pages - 1)
                    - self.scheduler.allocator.available
                )
                / max(1, self.scheduler.config.num_pages - 1),
            ),
            Gauge(
                "llmq_engine_batch_occupancy",
                "Fraction of decode slots holding a running sequence",
                fn=lambda: len(self.scheduler.running)
                / max(1, self.cfg.max_num_seqs),
            ),
            Gauge(
                "llmq_prefix_hit_pages",
                "KV pages reused via the prefix cache (device + host tier)",
                fn=lambda: self.scheduler.prefix_hits,
            ),
            Gauge(
                "llmq_prefix_miss_pages",
                "Full prompt pages that had to prefill (prefix cache miss)",
                fn=lambda: self.scheduler.prefix_misses,
            ),
            Gauge(
                "llmq_prefix_demote_pages",
                "Evicted device pages parked in the host prefix tier",
                fn=lambda: self.prefix_demotes,
            ),
            Gauge(
                "llmq_prefix_promote_pages",
                "Pages restored from the host prefix tier to device",
                fn=lambda: self.prefix_promotes,
            ),
            Gauge(
                "llmq_prefix_host_evictions",
                "Host prefix tier entries dropped by the byte-budget LRU",
                fn=lambda: (
                    self.prefix_store.evictions if self.prefix_store else 0
                ),
            ),
            Gauge(
                "llmq_prefix_host_bytes",
                "Host prefix tier occupancy in bytes",
                fn=lambda: (
                    self.prefix_store.occupancy_bytes
                    if self.prefix_store
                    else 0
                ),
            ),
            Gauge(
                "llmq_prefix_host_entries",
                "Host prefix tier resident page count",
                fn=lambda: (
                    len(self.prefix_store) if self.prefix_store else 0
                ),
            ),
            Gauge(
                "llmq_priority_preemptions",
                "Batch sequences preempted so interactive work could admit",
                fn=lambda: self.priority_preemptions,
            ),
            Gauge(
                "llmq_class_tokens",
                "Tokens generated for interactive-class requests",
                labels={"class": "interactive"},
                fn=lambda: self.class_tokens["interactive"],
            ),
            Gauge(
                "llmq_class_tokens",
                "Tokens generated for batch-class requests",
                labels={"class": "batch"},
                fn=lambda: self.class_tokens["batch"],
            ),
            Gauge(
                "llmq_class_finished",
                "Interactive-class requests finished cleanly (goodput)",
                labels={"class": "interactive"},
                fn=lambda: self.class_finished["interactive"],
            ),
            Gauge(
                "llmq_class_finished",
                "Batch-class requests finished cleanly (goodput)",
                labels={"class": "batch"},
                fn=lambda: self.class_finished["batch"],
            ),
            Gauge(
                "llmq_integrity_guard_trips",
                "Dispatches whose on-device logit guard fired",
                fn=lambda: self.guard_trips,
            ),
            Gauge(
                "llmq_integrity_weight_audit_mismatches",
                "Parameter leaves whose HBM digest diverged from the "
                "build-time baseline",
                fn=lambda: self.weight_audit_mismatches,
            ),
            Gauge(
                "llmq_integrity_canary_failures",
                "Golden-prompt canary replays that were not bit-exact",
                fn=lambda: self.canary_failures,
            ),
        ):
            reg.register(metric)

        self._resync()
        if os.environ.get("LLMQ_PARAM_AUTO_LAYOUT", "0") == "1":
            self._optimize_param_layouts()

        # Dispatch watchdog (default off): deadlines read the per-kind
        # histograms above, so it starts last. p99 comes from the live
        # distribution; kinds with no history (cold start, snapshot
        # gathers) fall back to the floor inside deadline_for.
        if self.watchdog_mult > 0:
            self.watchdog = DispatchWatchdog(
                mult=self.watchdog_mult,
                min_s=self.watchdog_min_s,
                percentile_fn=self._dispatch_p99,
            )
            logger.info(
                "dispatch watchdog: p99 x %.1f, floor %.1fs",
                self.watchdog_mult,
                self.watchdog_min_s,
            )

        # Integrity baselines, recorded last so they see the final
        # (possibly re-laid-out) parameters and a fully working engine.
        if self.weight_audit_every > 0:
            from llmq_tpu.engine import integrity as integrity_mod

            with self._wd("weight_audit"):
                self._weight_baseline = integrity_mod.digest_params(
                    self.params
                )
            self._next_weight_audit = (
                time.monotonic() + self.weight_audit_every
            )
            logger.info(
                "weight audit: %d leaves digested, sweeping every %.1fs",
                len(self._weight_baseline),
                self.weight_audit_every,
            )
        if self.canary_every > 0:
            self._canary_golden = self._generate_canary()
            self._next_canary = time.monotonic() + self.canary_every
            logger.info(
                "canary self-test: %d golden tokens, replaying every %.1fs",
                len(self._canary_golden),
                self.canary_every,
            )

    def _dispatch_p99(self, kind: str) -> Optional[float]:
        """Watchdog deadline source: live p99 of one dispatch kind, or
        None (→ floor) before any dispatch of that kind landed. Reads a
        histogram the engine thread appends to; bucket counts are ints,
        so a torn read costs at most one stale observation."""
        hist = self._dispatch_hists.get(kind)
        if hist is None:
            return None
        return hist.percentile(0.99)

    def _wd(self, kind: str):
        """Watchdog bracket for one device dispatch/fetch boundary; the
        shared no-op context when the watchdog is off (the default), so
        the hot path stays allocation-free and byte-identical."""
        wd = self.watchdog
        return NO_GUARD if wd is None else wd.guard(kind)

    def stop_watchdog(self) -> None:
        """Stop the monitor thread (engine teardown / fault rebuild)."""
        if self.watchdog is not None:
            self.watchdog.stop()

    # --- compilation ------------------------------------------------------
    def _build_steps(self) -> None:
        model = self.model
        S = self.cfg.max_num_seqs
        spec = self.cfg.spec_tokens > 0
        # On-device logit guard (default off → every closure below traces
        # the literal pre-existing program). When on, each step also
        # returns (stats f32[3], bad bool[rows]) folded from its logits;
        # thresholds are trace-time constants.
        guard = self.logit_guard == "on"
        g_max, g_ent = self.guard_logit_max, self.guard_entropy_min

        def guard_stats(logits, mask):
            return _dispatch.logit_guard_stats(
                logits, mask, max_abs=g_max, min_entropy=g_ent
            )

        # Device decode-state layout (leaf order is load-bearing):
        # 0 tokens[S]  1 ctx[S]    2 bt[S,pps]  3 active[S]  4 keys[S,kd]
        # 5 steps[S]   6 temps[S]  7 topks[S]   8 topps[S]   9 limits[S]
        # 10 mins[S]   11 stop_ids[S,E]
        # Speculative decoding appends leaf 12: history[S, max_model_len]
        # (prompt+output tokens; history[ctx] is the current token) —
        # the on-device drafter's lookup corpus. spec_tokens=0 builds
        # the exact 12-leaf state and functions as before.
        def advance_state(st, out, active):
            (tokens, ctx, bt, _, keys, steps, temps, topks, topps,
             limits, mins, stop_ids) = st
            new_steps = steps + active.astype(steps.dtype)
            hit_stop = jnp.logical_and(
                (out[:, None] == stop_ids).any(axis=1), new_steps > mins
            )
            hit_limit = new_steps >= limits
            still = jnp.logical_and(
                active,
                jnp.logical_not(jnp.logical_or(hit_stop, hit_limit)),
            )
            return (
                jnp.where(active, out, tokens),
                ctx + active.astype(ctx.dtype),
                bt,
                still,
                keys,
                new_steps,
                temps,
                topks,
                topps,
                limits,
                mins,
                stop_ids,
            )

        def suppress_stops(logits, stop_ids, steps, mins):
            """Mask stop/EOS logits while a slot is under min_tokens, so
            the forbidden token can never be sampled (vLLM semantics)."""

            def apply(logits):
                V = logits.shape[1]
                ids = jnp.where(stop_ids < 0, V, stop_ids)  # pad → OOB → drop
                rows = jnp.broadcast_to(
                    jnp.arange(ids.shape[0])[:, None], ids.shape
                )
                masked = logits.at[rows, ids].set(
                    sampling_mod.NEG_INF, mode="drop"
                )
                return jnp.where((steps < mins)[:, None], masked, logits)

            # min_tokens is rare; the scatter + full-logits rewrite costs
            # ~0.7 ms/step on [192, 152k] (measured) — skip it on device
            # unless some slot is actually still under its minimum.
            return jax.lax.cond(
                jnp.any(steps < mins), apply, lambda l: l, logits
            )

        def decode_step(params, kp, vp, st, *, mode, h=None):
            (tokens, ctx, bt, active, keys, steps, temps, topks,
             topps, _limits, mins, stop_ids) = st
            logits, kp, vp = model.decode(
                params, tokens, ctx, kp, vp, bt, active, h=h
            )
            # Guard reads the raw model logits: suppress_stops writes
            # NEG_INF sentinels that would false-trip the magnitude lane.
            g = guard_stats(logits, active) if guard else None
            logits = suppress_stops(logits, stop_ids, steps, mins)
            next_tokens = sample_tokens(
                logits, keys, steps, temps, topks, topps, mode=mode
            )
            out = jnp.where(active, next_tokens, 0)
            new_st = advance_state(st, out, active)
            if guard:
                return (out, g), kp, vp, new_st
            return out, kp, vp, new_st

        def decode_block_step(params, kp, vp, st, *, mode, k=None):
            """``decode_block`` fused decode iterations in ONE XLA
            computation: a ``lax.scan`` over ``decode_step`` carrying
            (kv pools, decode state) and stacking the per-iteration
            token vectors into a [K, S] block. Everything the host used
            to do between steps happens on device instead: the sampling
            key chain advances because ``advance_state`` increments the
            carried per-slot step counters that ``sample_tokens`` folds
            into the (fixed) base keys, and per-row stopping works
            because ``advance_state`` deactivates finished rows, whose
            remaining iterations then emit token 0 and write no KV
            (positions route to -1 / ctx_incl 0). Rows that finish at
            iteration j still ride out iterations j+1..K-1 inactive —
            the host discards those tokens when it processes the block.
            ``k`` overrides the scan length (the SLO scheduler's small-K
            interactive executable); the host side is shape-driven, so
            a [k, S] block processes exactly like a [K, S] one.
            """

            def body(carry, _):
                kp, vp, st = carry
                out, kp, vp, st = decode_step(params, kp, vp, st, mode=mode)
                return (kp, vp, st), out

            (kp, vp, st), outs = jax.lax.scan(
                body,
                (kp, vp, st),
                None,
                length=self.cfg.decode_block if k is None else k,
            )
            return outs, kp, vp, st

        M = self.cfg.max_model_len
        n_draft = self.cfg.spec_tokens
        n_gram = self.cfg.spec_ngram
        max_kv_pos = self._pages_per_seq * self.cfg.page_size

        def draft_lookup(history, ctx):
            """On-device prompt-lookup drafter: find the most recent
            earlier occurrence of the n_gram-token suffix ending at
            history[ctx] and propose the n_draft tokens that followed
            it. Rows with no match (or fewer than n_gram tokens so far)
            draft -1, which never equals an emitted token — the verify
            step then degenerates to exactly one non-speculative decode
            for that row. Overlapping matches are fine (repetition runs
            draft themselves), and stale tokens past ctx can never leak:
            gathers are clipped into the row and every draft is verified
            before it is emitted."""
            sfx_pos = ctx[:, None] - (n_gram - 1) + jnp.arange(n_gram)
            sfx = jnp.take_along_axis(
                history, jnp.clip(sfx_pos, 0, M - 1), axis=1
            )  # [S, n_gram]
            match = jnp.ones((S, M), bool)
            for t in range(n_gram):
                eq = history == sfx[:, t][:, None]
                # Shift so position p asks "does the n-gram ENDING at p
                # match the suffix" for every element at once.
                match &= jnp.roll(eq, (n_gram - 1) - t, axis=1)
            p_idx = jnp.arange(M)[None, :]
            match &= (
                (p_idx >= n_gram - 1)
                & (p_idx < ctx[:, None])
                & (ctx[:, None] + 1 >= n_gram)
            )
            j = jnp.max(jnp.where(match, p_idx, -1), axis=1)  # [S]
            d_pos = j[:, None] + 1 + jnp.arange(n_draft)[None, :]
            drafts = jnp.take_along_axis(
                history, jnp.clip(d_pos, 0, M - 1), axis=1
            )
            return jnp.where((j >= 0)[:, None], drafts, -1)

        def verify_step(params, kp, vp, st, *, mode):
            """One speculative decode iteration: draft, score all
            Q = spec_tokens+1 candidate positions in one model call
            (multi-query decode through the chunked-prefill attention
            path), accept the longest prefix the model itself emits,
            and advance per-row state by the accepted count. Rejected
            positions' KV stays in place — their sequence length simply
            doesn't advance past them, and the next verify step rewrites
            the same append-only positions. Emits ``(emit [S, Q],
            count [S])``: count = accepted drafts + 1 corrected/bonus
            token (0 for inactive rows); the host appends
            ``emit[row, :count]``."""
            (tokens, ctx, bt, active, keys, steps, temps, topks,
             topps, limits, mins, stop_ids, history) = st
            Q = n_draft + 1
            drafts = draft_lookup(history, ctx)  # [S, n_draft]
            qtok = jnp.concatenate(
                [tokens[:, None], jnp.maximum(drafts, 0)], axis=1
            )  # [S, Q]
            pos_grid = ctx[:, None] + jnp.arange(Q)[None, :]
            # Inactive rows and positions past the per-row page map route
            # to -1 (scratch page, no attention): an unmapped position
            # would otherwise clamp into the row's LAST mapped page and
            # corrupt it. The grid keeps the leading-contiguous-run form
            # the chunked-prefill kernel contract requires.
            qpos = jnp.where(
                active[:, None] & (pos_grid < max_kv_pos), pos_grid, -1
            )
            logits, kp, vp = model.verify(params, qtok, qpos, kp, vp, bt)
            V = logits.shape[-1]
            if guard:
                # Raw logits (pre suppress_stops sentinels); per-row
                # verdict folds the Q candidate positions of each slot.
                g_stats, g_bad = guard_stats(
                    logits.reshape(S * Q, V), jnp.repeat(active, Q)
                )
                g = (g_stats, g_bad.reshape(S, Q).any(axis=1))
            else:
                g = None
            steps_grid = steps[:, None] + jnp.arange(Q)[None, :]
            flat = suppress_stops(
                logits.reshape(S * Q, V),
                jnp.repeat(stop_ids, Q, axis=0),
                steps_grid.reshape(-1),
                jnp.repeat(mins, Q),
            )
            emit = sampling_mod.spec_verify_tokens(
                flat.reshape(S, Q, V), drafts, keys, steps,
                temps, topks, topps, mode=mode,
            )  # [S, Q]
            # Position i is reached iff every earlier draft was accepted
            # (emit == draft); position 0 (the normal decode token) is
            # always reached on active rows.
            reached = jnp.concatenate(
                [
                    jnp.ones((S, 1), bool),
                    jnp.cumprod(
                        (emit[:, :-1] == drafts).astype(jnp.int32), axis=1
                    ).astype(bool),
                ],
                axis=1,
            )
            # Stopping mirrors advance_state per position: a stop/limit
            # hit at position i emits i's token and cuts everything after.
            new_steps_grid = steps_grid + 1
            hit_stop = (
                (emit[:, :, None] == stop_ids[:, None, :]).any(axis=2)
                & (new_steps_grid > mins[:, None])
            )
            stop_here = hit_stop | (new_steps_grid >= limits[:, None])
            stopped_before = (
                jnp.cumsum(stop_here.astype(jnp.int32), axis=1)
                - stop_here.astype(jnp.int32)
            ) > 0
            emitted = active[:, None] & reached & ~stopped_before  # [S, Q]
            count = emitted.sum(axis=1).astype(ctx.dtype)  # [S]
            new_tok = jnp.take_along_axis(
                emit, jnp.maximum(count - 1, 0)[:, None], axis=1
            )[:, 0]
            still = active & ~(emitted & stop_here).any(axis=1)
            rows = jnp.broadcast_to(jnp.arange(S)[:, None], (S, Q))
            hist_pos = jnp.where(emitted, pos_grid + 1, M)  # OOB → drop
            st = (
                jnp.where(count > 0, new_tok, tokens),
                ctx + count,
                bt,
                still,
                keys,
                steps + count,
                temps,
                topks,
                topps,
                limits,
                mins,
                stop_ids,
                history.at[rows, hist_pos].set(emit, mode="drop"),
            )
            ys = (jnp.where(emitted, emit, 0), count)
            if guard:
                return (ys, g), kp, vp, st
            return ys, kp, vp, st

        def verify_block_step(params, kp, vp, st, *, mode, k=None):
            """decode_block fused verify iterations in one XLA
            computation, mirroring decode_block_step. Always a lax.scan
            (even K=1) so the output block is uniformly ([K, S, Q]
            tokens, [K, S] accept counts). ``k`` overrides the scan
            length for the small-K interactive executable."""

            def body(carry, _):
                kp, vp, st = carry
                ys, kp, vp, st = verify_step(params, kp, vp, st, mode=mode)
                return (kp, vp, st), ys

            (kp, vp, st), outs = jax.lax.scan(
                body,
                (kp, vp, st),
                None,
                length=self.cfg.decode_block if k is None else k,
            )
            return outs, kp, vp, st

        def sample_and_scatter(logits, valid, p_lengths, p_bt, p_slots,
                               p_keys, p_steps, p_temps, p_topks, p_topps,
                               p_limits, p_mins, p_stopids, st, *, mode,
                               p_history=None):
            """Shared tail of the prefill variants: sample each valid
            row's first token and scatter the row into the decode state
            (invalid rows route out of range and are dropped)."""
            logits = suppress_stops(logits, p_stopids, p_steps, p_mins)
            nt = sample_tokens(
                logits, p_keys, p_steps, p_temps, p_topks, p_topps, mode=mode
            )
            out = jnp.where(valid, nt, 0)
            new_steps = p_steps + 1
            hit_stop = jnp.logical_and(
                (out[:, None] == p_stopids).any(axis=1), new_steps > p_mins
            )
            alive = jnp.logical_and(
                valid,
                jnp.logical_not(
                    jnp.logical_or(hit_stop, new_steps >= p_limits)
                ),
            )
            idx = jnp.where(valid, p_slots, S)
            (tokens, ctx, bt, active, keys, steps, temps, topks, topps,
             limits, mins, stop_ids, *hist) = st
            st = (
                tokens.at[idx].set(out, mode="drop"),
                ctx.at[idx].set(p_lengths, mode="drop"),
                bt.at[idx].set(p_bt, mode="drop"),
                active.at[idx].set(alive, mode="drop"),
                keys.at[idx].set(p_keys, mode="drop"),
                steps.at[idx].set(new_steps, mode="drop"),
                temps.at[idx].set(p_temps, mode="drop"),
                topks.at[idx].set(p_topks, mode="drop"),
                topps.at[idx].set(p_topps, mode="drop"),
                limits.at[idx].set(p_limits, mode="drop"),
                mins.at[idx].set(p_mins, mode="drop"),
                stop_ids.at[idx].set(p_stopids, mode="drop"),
            )
            if spec:
                # Keep the drafter's invariant history[ctx] == current
                # token: the row's prompt+output plus its fresh first
                # sample at position p_lengths (== the new ctx).
                B = p_history.shape[0]
                hrow = p_history.at[jnp.arange(B), p_lengths].set(
                    out, mode="drop"
                )
                st += (hist[0].at[idx].set(hrow, mode="drop"),)
            return out, st

        def prefill_step(params, kp, vp, p_tokens, p_lengths, p_bt, p_slots,
                         p_keys, p_steps, p_temps, p_topks, p_topps,
                         p_limits, p_mins, p_stopids, *rest, mode, h=None):
            # rest = (p_history, st) under speculation, (st,) otherwise.
            p_history, st = rest if spec else (None, rest[0])
            logits, kp, vp = model.prefill(
                params, p_tokens, p_lengths, kp, vp, p_bt, h=h
            )
            g = guard_stats(logits, p_slots >= 0) if guard else None
            out, st = sample_and_scatter(
                logits, p_slots >= 0, p_lengths, p_bt, p_slots, p_keys,
                p_steps, p_temps, p_topks, p_topps, p_limits, p_mins,
                p_stopids, st, mode=mode, p_history=p_history,
            )
            if guard:
                return (out, g), kp, vp, st
            return out, kp, vp, st

        def chunkfill_step(params, kp, vp, c_tokens, c_positions, c_bt,
                           c_final, c_last, c_lengths, c_slots, c_keys,
                           c_steps, c_temps, c_topks, c_topps, c_limits,
                           c_mins, c_stopids, *rest, mode, h=None):
            """One chunk of prompt positions for up to B rows. Rows whose
            prompt ENDS in this chunk (c_final) sample their first token
            and scatter into the decode state exactly like prefill_step;
            other rows only extend their cached K/V."""
            c_history, st = rest if spec else (None, rest[0])
            logits, kp, vp = model.prefill_chunk(
                params, c_tokens, c_positions, kp, vp, c_bt, c_last, h=h
            )
            # Guard watches every valid row's chunk logits (non-final
            # rows too: mid-prompt logits are real model outputs, so
            # corruption surfaces chunks before the first sample).
            g = guard_stats(logits, c_slots >= 0) if guard else None
            out, st = sample_and_scatter(
                logits, jnp.logical_and(c_slots >= 0, c_final), c_lengths,
                c_bt, c_slots, c_keys, c_steps, c_temps, c_topks, c_topps,
                c_limits, c_mins, c_stopids, st, mode=mode,
                p_history=c_history,
            )
            if guard:
                return (out, g), kp, vp, st
            return out, kp, vp, st

        def mixedfill_step(params, kp, vp, m_tokens, m_positions, m_final,
                           m_last, m_bt, m_lengths, m_slots, m_keys,
                           m_steps, m_temps, m_topks, m_topps, m_limits,
                           m_mins, m_stopids, *rest, mode):
            """Piggyback scheduling: ONE fused dispatch runs decode_block
            iterations that each decode the running batch AND prefill one
            token-budgeted segment of a single pending prompt through the
            shared paged-attention path (``model.mixed`` — the same
            write-then-attend chunk trunk verify uses). The decode rows'
            math is exactly ``decode_step``'s, so greedy outputs are
            token-identical to the unfused engine; the prefill rides in
            the MXU bubble the bandwidth-bound decode leaves behind.

            Per-iteration inputs (scanned, leading axis K): segment
            tokens/positions ``[K, C]`` (−1-padded, leading-contiguous),
            ``m_final [K]`` (does this segment reach the prompt's last
            position) and ``m_last [K]`` (its in-segment index). The
            per-row args describe the ONE piggy sequence (shape [1, ...],
            same pack as the chunked-prefill group invariants). When the
            final segment lands at iteration k < K−1, the scatter
            activates the piggy's slot and the REMAINING iterations of
            this very scan decode it alongside the batch — the host
            pre-allocated pages for those in-dispatch positions. An
            all-(−1) segment is a pure decode iteration (re-planned
            page-pressure dispatches use these as middles)."""
            m_history, st = rest if spec else (None, rest[0])
            slot = m_slots[0]

            def body(carry, xs):
                kp, vp, st = carry
                seg_tokens, seg_positions, seg_final, seg_last = xs
                (tokens, ctx, bt, active, keys, steps, temps, topks,
                 topps, limits, mins, stop_ids, *hist) = st
                qtok, qpos, is_chunk = mixed_query_grid(
                    tokens, ctx, active, seg_tokens, seg_positions,
                    slot, max_kv_pos,
                )
                gather = jnp.where(is_chunk, seg_last, 0)
                # The piggy's block table rides in via m_bt: its pages
                # join the decode state only at the final-segment
                # scatter, and shipping it per dispatch also delivers
                # mid-prefill growth without a block-table swap.
                bt_used = bt.at[slot].set(m_bt[0])
                logits, kp, vp = model.mixed(
                    params, qtok, qpos, kp, vp, bt_used, gather
                )
                if guard:
                    # Active decode rows, plus the piggy's slot row on
                    # the iteration whose segment samples its first
                    # token (earlier segments gather pad positions).
                    g_mask = jnp.logical_or(
                        active,
                        (jnp.arange(S) == slot)
                        & seg_final
                        & (m_slots[0] >= 0),
                    )
                    g = guard_stats(logits, g_mask)
                else:
                    g = None
                # Decode tail — identical math to decode_step for the
                # active rows (the chunk row is inactive, emits 0 here).
                d_logits = suppress_stops(logits, stop_ids, steps, mins)
                next_tokens = sample_tokens(
                    d_logits, keys, steps, temps, topks, topps, mode=mode
                )
                out = jnp.where(active, next_tokens, 0)
                st12 = advance_state(st[:12], out, active)
                if spec:
                    # Drafting pauses during mixed dispatches (plain
                    # decode — still lossless); keep the invariant
                    # history[ctx] == current token so the drafter
                    # resumes coherently on the next verify dispatch.
                    st = st12 + (
                        hist[0].at[
                            jnp.arange(S), jnp.where(active, ctx + 1, M)
                        ].set(out, mode="drop"),
                    )
                else:
                    st = st12
                # Piggy activation AFTER the decode advance: the final
                # segment's last position samples the first token and
                # scatters the row into the decode state, so the next
                # iteration of this scan decodes it.
                out1, st = sample_and_scatter(
                    logits[slot][None],
                    seg_final[None] & (m_slots >= 0),
                    m_lengths, m_bt, m_slots, m_keys, m_steps, m_temps,
                    m_topks, m_topps, m_limits, m_mins, m_stopids, st,
                    mode=mode, p_history=m_history,
                )
                emit = jnp.where(
                    (jnp.arange(S) == slot) & seg_final, out1[0], out
                )
                if guard:
                    return (kp, vp, st), (emit, g)
                return (kp, vp, st), emit

            (kp, vp, st), outs = jax.lax.scan(
                body, (kp, vp, st), (m_tokens, m_positions, m_final, m_last)
            )
            return outs, kp, vp, st

        def mixed_iter(params, kp, vp, h, seg_tokens, seg_positions,
                       seg_final, seg_last, m_bt, m_lengths, m_slots,
                       m_keys, m_steps, m_temps, m_topks, m_topps,
                       m_limits, m_mins, m_stopids, st, *, mode):
            """ONE iteration of the mixed scan body, h-threaded — the pp
            head-stage executable (the host drives the K loop because
            every iteration's hidden states cross stage boundaries).
            Math is line-for-line the scan body above minus the guard and
            speculation branches, both of which are gated off under pp."""
            slot = m_slots[0]
            (tokens, ctx, bt, active, keys, steps, temps, topks,
             topps, limits, mins, stop_ids) = st
            qtok, qpos, is_chunk = mixed_query_grid(
                tokens, ctx, active, seg_tokens, seg_positions,
                slot, max_kv_pos,
            )
            gather = jnp.where(is_chunk, seg_last, 0)
            bt_used = bt.at[slot].set(m_bt[0])
            logits, kp, vp = model.mixed(
                params, qtok, qpos, kp, vp, bt_used, gather, h=h
            )
            d_logits = suppress_stops(logits, stop_ids, steps, mins)
            next_tokens = sample_tokens(
                d_logits, keys, steps, temps, topks, topps, mode=mode
            )
            out = jnp.where(active, next_tokens, 0)
            st = advance_state(st, out, active)
            out1, st = sample_and_scatter(
                logits[slot][None],
                seg_final[None] & (m_slots >= 0),
                m_lengths, m_bt, m_slots, m_keys, m_steps, m_temps,
                m_topks, m_topps, m_limits, m_mins, m_stopids, st,
                mode=mode,
            )
            emit = jnp.where(
                (jnp.arange(S) == slot) & seg_final, out1[0], out
            )
            return emit, kp, vp, st

        repl, slot1, slot2 = self._repl, self._slot1, self._slot2
        kv = self._kv_format
        st_sh = (slot1, slot1, slot2, slot1, slot2, slot1, slot1, slot1,
                 slot1, slot1, slot1, slot2)
        if spec:
            st_sh += (slot2,)  # history[S, M]
        self._st_shardings = st_sh
        self._prefill_arg_shardings = (repl,) * (13 if spec else 12)
        self._decode_fn = decode_step
        self._decode_block_fn = decode_block_step
        self._verify_block_fn = verify_block_step
        self._prefill_fn = prefill_step
        self._chunkfill_fn = chunkfill_step
        self._mixedfill_fn = mixedfill_step
        self._mixed_iter_fn = mixed_iter
        if self.pp > 1:
            self._build_pp_jits(
                decode_step=decode_step,
                prefill_step=prefill_step,
                chunkfill_step=chunkfill_step,
                mixed_iter=mixed_iter,
            )
            return
        self._make_jits(self._param_shardings)

    def _make_jits(self, param_spec) -> None:
        """(Re)build the per-mode compiled steps with ``param_spec`` as the
        parameter in_sharding (NamedShardings, or pinned Formats after
        ``_optimize_param_layouts``). One executable per sampler variant
        actually used: a greedy batch must not pay the [S, V] vocab sort
        (sampling.required_mode); jit compiles lazily, so unused variants
        cost nothing. Prefill gets the same per-mode treatment (~19 ms per
        8x256 chunk of filter machinery at a 152k vocab, measured round 3).
        """
        repl, slot1 = self._repl, self._slot1
        kv = self._kv_format
        st_sh = self._st_shardings
        # decode_block > 1 swaps in the fused K-iteration scan: same
        # signature and donation, token output [K, S] instead of [S]
        # (the host normalises both to 2-D when processing). K == 1
        # keeps literally the pre-block executable. Speculation swaps in
        # the fused verify scan, whose token output is the tuple
        # ([K, S, Q] candidates, [K, S] accept counts); with
        # spec_tokens == 0 none of this branch exists and the decode
        # executable is bit-for-bit the non-speculative one.
        if self.cfg.spec_tokens > 0:
            fn, out0 = self._verify_block_fn, (self._spec_out, self._block1)
        elif self.cfg.decode_block > 1:
            fn, out0 = self._decode_block_fn, self._block1
        else:
            fn, out0 = self._decode_fn, slot1
        # Logit guard on: every step's token output pairs with the tiny
        # (stats, bad-rows) guard fold — replicated, it rides the same
        # async fetch as the tokens. Off: the out specs (and programs)
        # are untouched.
        g_on = self.logit_guard == "on"
        guard_sh = (repl, repl)
        if g_on:
            out0 = (out0, guard_sh)
        p_out = (repl, guard_sh) if g_on else repl
        self._decode_jits = {
            mode: jax.jit(
                partial(fn, mode=mode),
                in_shardings=(param_spec, kv, kv, st_sh),
                out_shardings=(out0, kv, kv, st_sh),
                donate_argnums=(1, 2, 3),
            )
            for mode in ("greedy", "stochastic", "filtered")
        }
        # SLO small-K interactive variant: the SAME block/verify scan at
        # interactive_decode_block iterations — a second executable with
        # identical sharding and donation contracts (out specs carry no
        # shapes, so the [k, S] block reuses the big-K specs; the host
        # side is shape-driven and processes either). Dispatch picks it
        # whenever an interactive row is resident. Token parity per
        # request holds by construction: the scan body is the identical
        # decode_step, only the host-visit cadence changes.
        self._decode_jits_small = None
        ik = self.interactive_decode_block
        if 0 < ik < self.cfg.decode_block:
            s_fn = (
                self._verify_block_fn
                if self.cfg.spec_tokens > 0
                else self._decode_block_fn
            )
            s_out0 = (
                (self._spec_out, self._block1)
                if self.cfg.spec_tokens > 0
                else self._block1
            )
            if g_on:
                s_out0 = (s_out0, guard_sh)
            self._decode_jits_small = {
                mode: jax.jit(
                    partial(s_fn, mode=mode, k=ik),
                    in_shardings=(param_spec, kv, kv, st_sh),
                    out_shardings=(s_out0, kv, kv, st_sh),
                    donate_argnums=(1, 2, 3),
                )
                for mode in ("greedy", "stochastic", "filtered")
            }
        # Prefill data args grow by one (the per-row history) under
        # speculation; the trailing decode-state arg shifts with them.
        nP = len(self._prefill_arg_shardings)  # 13 if spec else 12
        self._prefill_jits = {
            mode: jax.jit(
                partial(self._prefill_fn, mode=mode),
                in_shardings=(param_spec, kv, kv) + (repl,) * nP + (st_sh,),
                out_shardings=(p_out, kv, kv, st_sh),
                donate_argnums=(1, 2, 3 + nP),
            )
            for mode in ("greedy", "stochastic", "filtered")
        }
        nC = nP + 3  # chunk args: 5 per-chunk + (10|11) group-invariant
        self._chunkfill_jits = {
            mode: jax.jit(
                partial(self._chunkfill_fn, mode=mode),
                in_shardings=(param_spec, kv, kv) + (repl,) * nC + (st_sh,),
                out_shardings=(p_out, kv, kv, st_sh),
                donate_argnums=(1, 2, 3 + nC),
            )
            for mode in ("greedy", "stochastic", "filtered")
        }
        # Snapshot plane: whole-page KV scatter for insert_request /
        # swap-to-host restore. Same donation-and-format discipline as the
        # decode steps — the pool buffer is reused in place and the
        # result keeps the pool's pinned layout+sharding, so restores
        # compose with run-ahead dispatch. Retraces per distinct page
        # count; restores are rare (preemption under pressure, handoff),
        # so the retrace cost is noise.
        self._kv_insert_jit = jax.jit(
            _dispatch.insert_kv_pages,
            in_shardings=(kv, repl, repl),
            out_shardings=kv,
            donate_argnums=(0,),
        )
        # Piggyback scheduling: built only when resolved on — an "off"
        # engine carries literally the pre-existing executables. Token
        # output is a [K, S] block like fused decode.
        if self.mixed_step == "on":
            nM = nP + 3  # 4 per-iteration [K, ...] + (11|12) piggy-row args
            self._mixedfill_jits = {
                mode: jax.jit(
                    partial(self._mixedfill_fn, mode=mode),
                    in_shardings=(param_spec, kv, kv)
                    + (repl,) * nM
                    + (st_sh,),
                    out_shardings=(
                        (self._block1, guard_sh) if g_on else self._block1,
                        kv,
                        kv,
                        st_sh,
                    ),
                    donate_argnums=(1, 2, 3 + nM),
                )
                for mode in ("greedy", "stochastic", "filtered")
            }

    def _build_pp_jits(
        self, *, decode_step, prefill_step, chunkfill_step, mixed_iter
    ) -> None:
        """Stage-partitioned executables + the host drivers that chain
        them (pp > 1). Each NON-HEAD stage compiles one executable per
        dispatch kind over its own 3-axis submesh — (stage params, stage
        KV pools, data args[, upstream hidden]) → (hidden grid, pools) —
        and the HEAD stage compiles the existing per-mode step closures
        with the upstream hidden threaded in, so sampling, decode-state
        advance and donation are bit-for-bit the pp=1 programs. The
        drivers installed into ``_decode_jits``/``_prefill_jits``/
        ``_chunkfill_jits``/``_mixedfill_jits`` keep the pp=1 call
        signatures (kp/vp become per-stage lists), which leaves every
        dispatch site untouched.

        GPipe microbatching falls out of the call structure: prefill
        chunks are the microbatches (the chunk loop keeps stage s busy on
        chunk i+1 while stage s+1 runs chunk i, because every jit call
        here is an async dispatch), and decode amortizes fill/drain over
        ``decode_block`` iterations per dispatch × ``runahead`` dispatches
        in flight."""
        pp = self.pp
        repl = self._repl
        st_sh = self._st_shardings
        stage_params = self._param_shardings["stages"]
        self._stage_repl = [
            NamedSharding(m, P()) for m in self._stage_meshes
        ]
        max_kv_pos = self._pages_per_seq * self.cfg.page_size

        # --- per-stage (non-head) executables --------------------------
        def stage_jit(fn, s, n_data, with_h):
            kv_s = self._kv_formats[s]
            repl_s = self._stage_repl[s]
            n = n_data + (1 if with_h else 0)
            return jax.jit(
                fn,
                in_shardings=(stage_params[s], kv_s, kv_s)
                + (repl_s,) * n,
                out_shardings=(repl_s, kv_s, kv_s),
                donate_argnums=(1, 2),
            )

        def make_stage_fns(s):
            model_s = self._stage_models[s]
            first = s == 0

            if first:
                def dec(params, kp, vp, tokens, ctx, bt, active):
                    return model_s.decode(
                        params, tokens, ctx, kp, vp, bt, active,
                        return_hidden=True,
                    )

                def pre(params, kp, vp, tokens, lengths, bt):
                    return model_s.prefill(
                        params, tokens, lengths, kp, vp, bt,
                        return_hidden=True,
                    )

                def chk(params, kp, vp, tokens, positions, bt):
                    return model_s._paged_chunk_trunk(
                        params, tokens, positions, kp, vp, bt
                    )

                def mix(params, kp, vp, tokens, ctx, active, bt,
                        seg_tokens, seg_positions, seg_last, m_bt, m_slots):
                    slot = m_slots[0]
                    qtok, qpos, is_chunk = mixed_query_grid(
                        tokens, ctx, active, seg_tokens, seg_positions,
                        slot, max_kv_pos,
                    )
                    gather = jnp.where(is_chunk, seg_last, 0)
                    bt_used = bt.at[slot].set(m_bt[0])
                    return model_s.mixed(
                        params, qtok, qpos, kp, vp, bt_used, gather,
                        return_hidden=True,
                    )
            else:
                def dec(params, kp, vp, tokens, ctx, bt, active, h):
                    return model_s.decode(
                        params, tokens, ctx, kp, vp, bt, active, h=h,
                        return_hidden=True,
                    )

                def pre(params, kp, vp, tokens, lengths, bt, h):
                    return model_s.prefill(
                        params, tokens, lengths, kp, vp, bt, h=h,
                        return_hidden=True,
                    )

                def chk(params, kp, vp, tokens, positions, bt, h):
                    return model_s._paged_chunk_trunk(
                        params, tokens, positions, kp, vp, bt, h=h
                    )

                def mix(params, kp, vp, tokens, ctx, active, bt,
                        seg_tokens, seg_positions, seg_last, m_bt,
                        m_slots, h):
                    slot = m_slots[0]
                    qtok, qpos, is_chunk = mixed_query_grid(
                        tokens, ctx, active, seg_tokens, seg_positions,
                        slot, max_kv_pos,
                    )
                    gather = jnp.where(is_chunk, seg_last, 0)
                    bt_used = bt.at[slot].set(m_bt[0])
                    return model_s.mixed(
                        params, qtok, qpos, kp, vp, bt_used, gather, h=h,
                        return_hidden=True,
                    )
            return dec, pre, chk, mix

        self._pp_decode_stage = []
        self._pp_prefill_stage = []
        self._pp_chunk_stage = []
        self._pp_mixed_stage = []
        for s in range(pp - 1):
            dec, pre, chk, mix = make_stage_fns(s)
            with_h = s > 0
            self._pp_decode_stage.append(stage_jit(dec, s, 4, with_h))
            self._pp_prefill_stage.append(stage_jit(pre, s, 3, with_h))
            self._pp_chunk_stage.append(stage_jit(chk, s, 3, with_h))
            self._pp_mixed_stage.append(stage_jit(mix, s, 9, with_h))

        # --- head-stage executables (per sampler mode) -----------------
        head_sh = stage_params[-1]
        kv = self._kv_format  # head stage pool format

        def head_decode(params, kp, vp, h, st, *, mode):
            return decode_step(params, kp, vp, st, mode=mode, h=h)

        def head_prefill(params, kp, vp, h, *rest, mode):
            *data, st = rest
            return prefill_step(params, kp, vp, *data, st, mode=mode, h=h)

        def head_chunkfill(params, kp, vp, h, *rest, mode):
            *data, st = rest
            return chunkfill_step(
                params, kp, vp, *data, st, mode=mode, h=h
            )

        modes = ("greedy", "stochastic", "filtered")
        self._pp_decode_head = {
            mode: jax.jit(
                partial(head_decode, mode=mode),
                in_shardings=(head_sh, kv, kv, repl, st_sh),
                out_shardings=(self._slot1, kv, kv, st_sh),
                donate_argnums=(1, 2, 4),
            )
            for mode in modes
        }
        nP = len(self._prefill_arg_shardings)  # 12 (spec gated off)
        self._pp_prefill_head = {
            mode: jax.jit(
                partial(head_prefill, mode=mode),
                in_shardings=(head_sh, kv, kv, repl)
                + (repl,) * nP
                + (st_sh,),
                out_shardings=(repl, kv, kv, st_sh),
                donate_argnums=(1, 2, 4 + nP),
            )
            for mode in modes
        }
        nC = nP + 3
        self._pp_chunkfill_head = {
            mode: jax.jit(
                partial(head_chunkfill, mode=mode),
                in_shardings=(head_sh, kv, kv, repl)
                + (repl,) * nC
                + (st_sh,),
                out_shardings=(repl, kv, kv, st_sh),
                donate_argnums=(1, 2, 4 + nC),
            )
            for mode in modes
        }
        nM = nP + 3  # 4 per-iteration seg args + m_bt + 10 piggy-row args
        self._pp_mixed_head = {
            mode: jax.jit(
                partial(mixed_iter, mode=mode),
                in_shardings=(head_sh, kv, kv, repl)
                + (repl,) * nM
                + (st_sh,),
                out_shardings=(self._slot1, kv, kv, st_sh),
                donate_argnums=(1, 2, 4 + nM),
            )
            for mode in modes
        }
        # Per-stage KV whole-page scatter (restore/prefix-ingest path).
        self._kv_insert_jits = [
            jax.jit(
                _dispatch.insert_kv_pages,
                in_shardings=(
                    self._kv_formats[s],
                    self._stage_repl[s],
                    self._stage_repl[s],
                ),
                out_shardings=self._kv_formats[s],
                donate_argnums=(0,),
            )
            for s in range(pp)
        ]

        # --- host drivers (installed under the pp=1 jit-dict names) ----
        K = self.cfg.decode_block

        def decode_driver(params, kps, vps, st, *, mode):
            outs = []
            for _ in range(K):
                h = None
                for s in range(pp - 1):
                    t_s, c_s, b_s, a_s = self._ship(
                        (st[0], st[1], st[2], st[3]), s
                    )
                    if s == 0:
                        h, kps[0], vps[0] = self._pp_decode_stage[0](
                            params["stages"][0], kps[0], vps[0],
                            t_s, c_s, b_s, a_s,
                        )
                    else:
                        h, kps[s], vps[s] = self._pp_decode_stage[s](
                            params["stages"][s], kps[s], vps[s],
                            t_s, c_s, b_s, a_s, self._ship_h(h, s),
                        )
                out, kps[-1], vps[-1], st = self._pp_decode_head[mode](
                    params["stages"][-1], kps[-1], vps[-1],
                    self._ship_h(h, pp - 1), st,
                )
                outs.append(out)
            block = outs[0] if K == 1 else jnp.stack(outs)
            return block, kps, vps, st

        def prefill_driver(params, kps, vps, *rest, mode):
            *data, st = rest
            p_tokens, p_lengths, p_bt = data[0], data[1], data[2]
            h = None
            for s in range(pp - 1):
                t_s, l_s, b_s = self._ship((p_tokens, p_lengths, p_bt), s)
                if s == 0:
                    h, kps[0], vps[0] = self._pp_prefill_stage[0](
                        params["stages"][0], kps[0], vps[0], t_s, l_s, b_s
                    )
                else:
                    h, kps[s], vps[s] = self._pp_prefill_stage[s](
                        params["stages"][s], kps[s], vps[s],
                        t_s, l_s, b_s, self._ship_h(h, s),
                    )
            out, kps[-1], vps[-1], st = self._pp_prefill_head[mode](
                params["stages"][-1], kps[-1], vps[-1],
                self._ship_h(h, pp - 1), *data, st,
            )
            return out, kps, vps, st

        def chunkfill_driver(params, kps, vps, *rest, mode):
            *data, st = rest
            c_tokens, c_positions, c_bt = data[0], data[1], data[2]
            h = None
            for s in range(pp - 1):
                t_s, p_s, b_s = self._ship((c_tokens, c_positions, c_bt), s)
                if s == 0:
                    h, kps[0], vps[0] = self._pp_chunk_stage[0](
                        params["stages"][0], kps[0], vps[0], t_s, p_s, b_s
                    )
                else:
                    h, kps[s], vps[s] = self._pp_chunk_stage[s](
                        params["stages"][s], kps[s], vps[s],
                        t_s, p_s, b_s, self._ship_h(h, s),
                    )
            out, kps[-1], vps[-1], st = self._pp_chunkfill_head[mode](
                params["stages"][-1], kps[-1], vps[-1],
                self._ship_h(h, pp - 1), *data, st,
            )
            return out, kps, vps, st

        def mixedfill_driver(params, kps, vps, m_tokens, m_positions,
                             m_final, m_last, m_bt, *rest, mode):
            *inv, st = rest  # m_lengths, m_slots, ... m_stopids (10)
            m_slots = inv[1]
            outs = []
            for k in range(m_tokens.shape[0]):
                seg_t = m_tokens[k]
                seg_p = m_positions[k]
                seg_f = m_final[k]
                seg_l = m_last[k]
                h = None
                for s in range(pp - 1):
                    args_s = self._ship(
                        (st[0], st[1], st[3], st[2],
                         seg_t, seg_p, seg_l, m_bt, m_slots),
                        s,
                    )
                    tok_s, ctx_s, act_s, bt_s = args_s[:4]
                    sT, sP, sL, mb_s, ms_s = args_s[4:]
                    if s == 0:
                        h, kps[0], vps[0] = self._pp_mixed_stage[0](
                            params["stages"][0], kps[0], vps[0],
                            tok_s, ctx_s, act_s, bt_s,
                            sT, sP, sL, mb_s, ms_s,
                        )
                    else:
                        h, kps[s], vps[s] = self._pp_mixed_stage[s](
                            params["stages"][s], kps[s], vps[s],
                            tok_s, ctx_s, act_s, bt_s,
                            sT, sP, sL, mb_s, ms_s, self._ship_h(h, s),
                        )
                out, kps[-1], vps[-1], st = self._pp_mixed_head[mode](
                    params["stages"][-1], kps[-1], vps[-1],
                    self._ship_h(h, pp - 1),
                    seg_t, seg_p, seg_f, seg_l, m_bt, *inv, st,
                )
                outs.append(out)
            return jnp.stack(outs), kps, vps, st

        self._decode_jits = {
            mode: partial(decode_driver, mode=mode) for mode in modes
        }
        self._prefill_jits = {
            mode: partial(prefill_driver, mode=mode) for mode in modes
        }
        self._chunkfill_jits = {
            mode: partial(chunkfill_driver, mode=mode) for mode in modes
        }
        if self.mixed_step == "on":
            self._mixedfill_jits = {
                mode: partial(mixedfill_driver, mode=mode)
                for mode in modes
            }

    def _ship(self, arrays: tuple, s: int) -> tuple:
        """Copy per-dispatch data args onto stage ``s``'s submesh
        (replicated). Small control tensors — tokens, positions, block
        tables — not the activation payload; those go via _ship_h."""
        repl_s = self._stage_repl[s]
        return tuple(jax.device_put(a, repl_s) for a in arrays)

    def _ship_h(self, h, s: int):
        """Move a hidden-state grid across the stage boundary onto stage
        ``s``'s submesh. This is THE pipeline wire: device-to-device
        inside one process; with LLMQ_PP_WIRE=1 the grid round-trips
        through the snapshot wire codec first (serialize → frame →
        digest-check → decode), the in-process stand-in for the tcp://
        hop between stage hosts. Boundary accounting feeds the bench pp
        rung's bytes/token metric."""
        self.pp_boundary_transfers += 1
        self.pp_boundary_bytes += int(h.size) * int(h.dtype.itemsize)
        if self.pp_wire:
            # Runs inside the caller's dispatch watchdog bracket
            # (_wd("prefill"/"decode_block"/"mixed")), which times the
            # whole stage loop including this fetch.
            h = snapshot_mod.tensor_from_wire(  # llmq: ignore[unguarded-device-fetch]
                snapshot_mod.tensor_to_wire(np.asarray(h))
            )
        return jax.device_put(h, self._stage_repl[s])

    def _kv_gather_np(self, pages) -> Tuple[np.ndarray, np.ndarray]:
        """Gather pool pages to host as FULL-layer-stack (k, v) blobs.
        ``pages`` stays a host/numpy index so each eager gather follows
        its own pool's devices; under pp the per-stage layer slabs
        concatenate back to [L, n, page, H, D], so snapshots, swap blobs
        and prefix chunks are byte-identical to pp=1 (the wire format is
        pipeline-degree-agnostic). np.asarray blocks until each gather
        lands, so the host buffers are safe against later donation."""
        idx = np.asarray(pages, np.int32)  # llmq: ignore[unguarded-device-fetch]
        # Every call site holds _wd("snapshot_gather"), so these blocking
        # fetches are already inside a watchdog bracket.
        if self.pp == 1:
            k = np.asarray(_dispatch.gather_kv_pages(self.k_pages, idx))  # llmq: ignore[unguarded-device-fetch]
            v = np.asarray(_dispatch.gather_kv_pages(self.v_pages, idx))  # llmq: ignore[unguarded-device-fetch]
            return k, v
        ks = [
            np.asarray(_dispatch.gather_kv_pages(kp, idx))  # llmq: ignore[unguarded-device-fetch]
            for kp in self.k_pages
        ]
        vs = [
            np.asarray(_dispatch.gather_kv_pages(vp, idx))  # llmq: ignore[unguarded-device-fetch]
            for vp in self.v_pages
        ]
        return np.concatenate(ks, axis=0), np.concatenate(vs, axis=0)

    def _kv_insert_np(self, pages, k: np.ndarray, v: np.ndarray) -> None:
        """Scatter full-layer-stack host KV back into the pool(s),
        rebinding ``self.k_pages``/``self.v_pages`` to the donated
        results. Under pp the [L, ...] blob splits into per-stage slabs
        along the layer axis (the inverse of ``_kv_gather_np``)."""
        idx = np.asarray(pages, np.int32)  # llmq: ignore[unguarded-device-fetch]
        if self.pp == 1:
            self.k_pages = self._kv_insert_jit(
                self.k_pages, idx, np.ascontiguousarray(k)
            )
            self.v_pages = self._kv_insert_jit(
                self.v_pages, idx, np.ascontiguousarray(v)
            )
            return
        for s, (lo, hi) in enumerate(self._stage_ranges):
            self.k_pages[s] = self._kv_insert_jits[s](
                self.k_pages[s], idx, np.ascontiguousarray(k[lo:hi])
            )
            self.v_pages[s] = self._kv_insert_jits[s](
                self.v_pages[s], idx, np.ascontiguousarray(v[lo:hi])
            )

    def _optimize_param_layouts(self) -> None:
        """Pin parameters to the decode executable's PREFERRED layouts
        (LLMQ_PARAM_AUTO_LAYOUT=1). With default row-major inputs XLA
        re-layouts some stacked weights around every layer-scan slice
        (o/k/v_proj transpose copies, ~1.1 ms/step at 3B/192 slots —
        measured round 4); compiling once with AUTO input layouts and
        re-putting the params in whatever XLA chose removes those copies
        for every subsequent step. Costs one extra compile at startup."""
        if self.pp > 1:
            # The probe lowers the single-executable decode step; under
            # pp there is no such executable (per-stage programs + host
            # driver), so keep the default layouts.
            logger.info("param auto-layout skipped: pp > 1 engine")
            return
        auto_ps = jax.tree.map(
            lambda sh: Format(Layout.AUTO, sh), self._param_shardings
        )
        kv = self._kv_format
        # Probe the executable production actually dispatches: with
        # decode blocks (or speculative verify) the scan body's preferred
        # layouts are what the params should be pinned to.
        if self.cfg.spec_tokens > 0:
            fn, out0 = self._verify_block_fn, (self._spec_out, self._block1)
        elif self.cfg.decode_block > 1:
            fn, out0 = self._decode_block_fn, self._block1
        else:
            fn, out0 = self._decode_fn, self._slot1
        if self.logit_guard == "on":
            out0 = (out0, (self._repl, self._repl))
        probe = jax.jit(
            partial(fn, mode="greedy"),
            in_shardings=(auto_ps, kv, kv, self._st_shardings),
            out_shardings=(out0, kv, kv, self._st_shardings),
            donate_argnums=(1, 2, 3),
        )
        # Runs after _resync, so the state spec comes straight from the
        # live device state — no hand-maintained shape list to drift.
        sds = lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype)  # noqa: E731
        try:
            compiled = probe.lower(
                jax.tree.map(sds, self.params),
                sds(self.k_pages),
                sds(self.v_pages),
                jax.tree.map(sds, self._dev_state),
            ).compile()
            formats = compiled.input_formats[0][0]
        except Exception:  # noqa: BLE001 — backend without layout support
            logger.exception("param auto-layout probe failed; keeping defaults")
            return

        def reput(leaf, fmt):
            # Leaf-by-leaf with immediate delete: a whole-tree device_put
            # would briefly hold TWO full parameter copies in HBM, which
            # the auto-sized KV pool has not left room for. The in-flight
            # copy holds its own buffer reference, so delete() is safe —
            # but device_put returns the SAME array when the layout
            # already matches, and that one must survive.
            new = jax.device_put(leaf, fmt)
            if new is not leaf:
                leaf.delete()
            return new

        self.params = jax.tree.map(reput, self.params, formats)
        self._make_jits(formats)

    def _auto_num_pages(self) -> int:
        """Size the KV pool from device HBM (vLLM gpu_memory_utilization
        parity, ``vllm_worker.py:107``); conservative fallback off-TPU."""
        cfg = self.model_config
        tp = self.mesh.shape[TP_AXIS]
        kv_frac = 1.0 / tp if cfg.num_kv_heads % tp == 0 and tp > 1 else 1.0
        itemsize = jnp.dtype(self.cfg.kv_dtype).itemsize
        page_bytes_dev = int(
            2  # K and V
            * cfg.num_layers
            * self.cfg.page_size
            * cfg.num_kv_heads
            * cfg.head_dim_
            * itemsize
            * kv_frac
        )
        limit, used = None, 0
        try:
            stats = self.mesh.devices.flat[0].memory_stats()
            if stats:
                limit = stats.get("bytes_limit")
                used = stats.get("bytes_in_use", 0)
        except Exception:  # noqa: BLE001 — CPU backend has no memory_stats
            pass
        max_useful = (
            self.cfg.max_num_seqs
            * (-(-self.cfg.max_model_len // self.cfg.page_size) + 1)
            + 1
        )
        if limit is None:
            return min(max_useful, 4096)
        budget = int(limit * self.cfg.hbm_utilization) - used
        num = max(2, budget // page_bytes_dev)
        return int(min(num, max_useful))

    # --- request intake ---------------------------------------------------
    def add_request(
        self,
        rid: str,
        *,
        prompt: Optional[str] = None,
        messages: Optional[List[Dict[str, str]]] = None,
        prompt_ids: Optional[List[int]] = None,
        params: Optional[SamplingParams] = None,
        deadline_at: Optional[float] = None,
        prefill_only: bool = False,
        priority: str = "batch",
    ) -> Sequence:
        if prompt_ids is None:
            if messages is not None:
                prompt_ids = self.tokenizer.apply_chat_template(messages)
            elif prompt is not None:
                prompt_ids = self.tokenizer.encode(prompt)
            else:
                raise ValueError("request needs prompt, messages, or prompt_ids")
        if not prompt_ids:
            prompt_ids = [0]
        # Own copy: the scheduler caps max_tokens in place and a caller may
        # share one SamplingParams across requests.
        params = dataclasses.replace(params) if params else SamplingParams()
        need = len(
            set(params.stop_token_ids)
            | (set() if params.ignore_eos else self._eos_ids)
        )
        if need > self._stop_capacity:
            self._grow_stop_capacity(need)
        if priority not in ("interactive", "batch"):
            raise ValueError(
                f"priority={priority!r} (want interactive|batch)"
            )
        if not self.priority_classes:
            priority = "batch"  # classes disabled: everything is FIFO batch
        seq = Sequence(
            rid=rid,
            prompt_ids=list(prompt_ids),
            params=params,
            deadline_at=deadline_at,
            prefill_only=prefill_only,
            priority=priority,
        )
        if deadline_at is not None:
            self._deadlines_enabled = True
        if priority == "interactive" and not self._priority_enabled:
            # Lazily turn on priority-aware admission (like deadlines):
            # a fleet that never submits interactive work keeps the
            # exact pre-priority FIFO order and stats surface.
            self._priority_enabled = True
            self.scheduler.config.priority_aware = True
        self.total_prompt_tokens += len(seq.prompt_ids)
        self.scheduler.add(seq)
        return seq

    @property
    def has_work(self) -> bool:
        return (
            bool(self.scheduler.running)
            or self.scheduler.has_waiting
            or bool(self._pending)
        )

    # --- one engine iteration --------------------------------------------
    def step(self) -> List[RequestOutput]:
        """Admit + prefill new sequences, dispatch one decode step for the
        batch, process lagged results. Returns requests whose finish was
        *observed* this iteration (results lag dispatch by ≤ runahead).

        Admission drains the whole admissible backlog BEFORE the decode
        dispatch: a decode step costs the same at any occupancy (fixed
        shapes), so interleaving chunk/decode/chunk/decode through a
        refill wave runs full-cost steps at partial occupancy — admitting
        24 chunks back-to-back instead of staggered saves ~one step per
        chunk of the wave (~1.7 s over the 3B bench run, measured round 4
        analysis). Trickle arrivals still refill in one chunk, so serving
        latency is unchanged.
        """
        finished: List[RequestOutput] = []
        if self._deadlines_enabled:
            self._expire_deadlines(finished)
        if self._cancel_rids:
            self._sweep_cancels(finished)
        # Sequences decodable BEFORE this wave: only they justify
        # interleaving decode between admission chunks — a cold-start
        # wave decoding its own fresh rows would pay full-cost steps at
        # tiny occupancy, the exact waste batching the wave avoids.
        pre_wave = [s.rid for s in self._decodable_seqs()]
        while self._try_admit(finished):
            if any(rid in self.scheduler.running for rid in pre_wave):
                # Partial refill (e.g. 2 chunks admitted while 176 slots
                # decode): the decoders pay short stalls between chunks
                # instead of one long one.
                self._dispatch_decode(finished)
        if self.scheduler.running:
            self._dispatch_decode(finished)
        elif self._pending:
            self._process_oldest(finished)
        self._flush_deferred()
        return finished

    def _decodable_seqs(self) -> List[Sequence]:
        """Running sequences the decode step actually advances (prefilled;
        mid-prefill rows are in ``running`` but have no decode state)."""
        return [s for s in self.scheduler.running.values() if s.prefilled]

    def _expire_deadlines(self, finished: List[RequestOutput]) -> None:
        """Between-steps deadline sweep: waiting or running sequences
        whose wall-clock deadline has passed finish with
        ``deadline_exceeded`` — their slots and pages go to requests that
        can still meet theirs. Running mid-prefill rows are skipped (an
        in-flight chunk loop may still write their pages); they expire on
        the next sweep once prefilled."""
        now = time.time()
        for seq in [
            s
            for s in self.scheduler.waiting
            if s.deadline_at is not None and now > s.deadline_at
        ]:
            self.scheduler.waiting.remove(seq)
            self.scheduler.finish(seq, "deadline_exceeded")
            finished.append(self._output_for(seq))
            self.deadline_expirations += 1
        for seq in [
            s
            for s in self.scheduler.running.values()
            if s.prefilled and s.deadline_at is not None and now > s.deadline_at
        ]:
            self._finish_seq(
                seq, "deadline_exceeded", device_detected=False,
                finished=finished,
            )
            self.deadline_expirations += 1

    def cancel_request(self, rid: str) -> None:
        """Request cancellation of a waiting/running request (client
        disconnected mid-stream). Takes effect at the next step's sweep:
        the sequence finishes with ``finish_reason="cancelled"``, its
        slot and KV pages free through the normal deferred-release path,
        and the caller gets a RequestOutput like any other finish (so
        the job settles instead of redelivering). Safe to call with a
        rid this engine doesn't hold — the entry ages out."""
        self._cancel_rids[rid] = time.monotonic()

    def _sweep_cancels(self, finished: List[RequestOutput]) -> None:
        """Between-steps cancellation sweep, mirroring the deadline
        sweep: waiting sequences unqueue immediately; running prefilled
        sequences finish through ``_finish_seq`` (pages deferred, slot
        deactivated by the dirty resync). Mid-prefill rows are skipped —
        their in-flight chunk loop may still write their pages — and
        cancel on a later sweep once prefilled. Unknown rids age out
        after ``_CANCEL_TTL_S``."""
        now = time.monotonic()
        for seq in [
            s for s in self.scheduler.waiting if s.rid in self._cancel_rids
        ]:
            self.scheduler.waiting.remove(seq)
            self.scheduler.finish(seq, "cancelled")
            finished.append(self._output_for(seq))
            del self._cancel_rids[seq.rid]
            self.cancellations += 1
        for seq in [
            s
            for s in self.scheduler.running.values()
            if s.prefilled and s.rid in self._cancel_rids
        ]:
            self._finish_seq(
                seq, "cancelled", device_detected=False, finished=finished
            )
            del self._cancel_rids[seq.rid]
            self.cancellations += 1
        for rid, t in list(self._cancel_rids.items()):
            if now - t > _CANCEL_TTL_S:
                del self._cancel_rids[rid]

    def _interactive_victim(self) -> Optional[Sequence]:
        """Youngest running prefilled BATCH sequence — the preemption
        victim when interactive work would otherwise queue for a slot.
        Mid-prefill rows are never victims (their in-flight chunk loop
        would keep writing freed pages); interactive rows never evict
        each other (FIFO within the class)."""
        candidates = [
            s
            for s in self.scheduler.running.values()
            if s.prefilled and s.priority != "interactive"
        ]
        if not candidates:
            return None
        return max(candidates, key=lambda s: s.admitted_at)

    def _try_admit(self, finished: List[RequestOutput]) -> bool:
        """Admit + prefill up to one chunk; True if anything was admitted
        (the caller loops until the admissible backlog is drained)."""
        # Keep the pipeline's page-recycling cadence inside the wave:
        # processing entries past the runahead window advances
        # _processed_idx so deferred pages (from sequences that finished
        # just before the wave) return to the allocator BETWEEN chunks —
        # otherwise a tight pool cuts the wave short on OutOfPages that
        # next step's releases would have covered.
        while len(self._pending) > self.cfg.runahead:
            self._process_oldest(finished)
        self._flush_deferred()
        free = sum(s is None for s in self.scheduler.slots)
        # SLO preemption: an interactive waiter facing a full slot table
        # evicts the youngest prefilled batch victim (swap-preempt under
        # preempt_mode=swap — its KV gathers to host and scatters back on
        # re-admission) instead of queueing behind it. One victim per
        # admission round; the dirty resync the preemption forces is
        # paid by the prefill that follows anyway.
        int_waiting = self._priority_enabled and any(
            s.priority == "interactive" for s in self.scheduler.waiting
        )
        if int_waiting and free == 0 and self.priority_preempt:
            victim = self._interactive_victim()
            if victim is not None:
                self._self_preempt_deferred(victim)
                self.priority_preemptions += 1
                free = 1
        want = (
            min(
                self.cfg.max_prefill_batch,
                len(self.scheduler.waiting),
                len(self.scheduler.slots),  # a chunk can't exceed the slots
            )
            if self.scheduler.has_waiting
            else 0
        )
        # Batch admission: wait for enough free slots to fill a prefill
        # chunk rather than prefilling singletons as slots trickle free —
        # a B=1 chunk costs nearly a full weight pass for 1/B the tokens.
        # Never defer when nothing is running (no progress to wait for),
        # and never keep deferring past admit_max_wait_s. The clock starts
        # when work first *could* be admitted (waiting + a free slot) but
        # was deferred — NOT at enqueue: under a sustained backlog every
        # request is already "old" at head-of-line, which would turn every
        # freed slot into a B=1 prefill and defeat the deferral entirely.
        can_admit = bool(want) and free > 0
        full = free >= (want if self.scheduler.running else 1)
        if not can_admit or full:
            self._defer_since = None
        elif self._defer_since is None:
            self._defer_since = time.monotonic()
        overdue = (
            self._defer_since is not None
            and time.monotonic() - self._defer_since
            > self.cfg.admit_max_wait_s
        )
        # Interactive waiters never sit out the batch-admission deferral:
        # the latency that deferral trades away is exactly their SLO.
        if not (can_admit and (full or overdue or int_waiting)):
            return False
        self._defer_since = None
        admitted = self.scheduler.admit(max_new=self.cfg.max_prefill_batch)
        # Host-tier promotion runs BEFORE anything else touches the wave:
        # admit() already registered the promoted pages' hashes (so later
        # admits may share them), which is only sound if their KV lands
        # on device before any dispatch could read the pages.
        self._promote_host_pages(admitted)
        todo = []
        restored = []
        for seq in admitted:
            if seq.params.max_tokens <= 0:
                self.scheduler.finish(seq, "length")
                finished.append(self._output_for(seq))
                continue
            if seq.restore is not None:
                restored.append(seq)
            else:
                todo.append(seq)
        # Restores first: they mark the device state dirty, and the
        # prefill below (or the next decode dispatch) resyncs once for
        # the whole admission wave.
        if restored:
            self._restore_batch(restored)
        if todo:
            self._prefill_batch(todo, finished)
        return bool(admitted)

    # --- run-ahead pipeline ----------------------------------------------
    def _drain(self, finished: List[RequestOutput]) -> None:
        while self._pending:
            self._process_oldest(finished)
        self._flush_deferred()

    def _process_oldest(self, finished: List[RequestOutput]) -> None:
        idx, kind, out, snapshot, g = self._pending.popleft()
        if kind in ("decode", "mixed"):
            self._pending_decodes -= 1
        if g is not None:
            # Evaluate the guard verdict BEFORE appending any of this
            # dispatch's tokens: a tripped dispatch's outputs are suspect
            # and must not reach user-visible sequences. The raise routes
            # into the numerical-fault recovery (blame attribution).
            self._eval_guard(kind, g, snapshot)
        if kind == "mixed":
            # Mixed dispatch: ([K, S] token block, per-row first-valid
            # iteration). Decode rows start at 0; the piggy row's tokens
            # before its final-segment iteration are padding zeros from
            # its inactive phase and must be skipped, not appended.
            block, starts = out
            with self._wd("mixed"):
                tokens = np.asarray(block)
            for k in range(tokens.shape[0]):
                for row, seq, epoch in snapshot:
                    if k < starts[row]:
                        continue
                    if (
                        seq.finish_reason is not None
                        or seq.rid not in self.scheduler.running
                        or seq.epoch != epoch
                    ):
                        continue
                    self._append_and_check(seq, int(tokens[k, row]), finished)
            self._processed_idx = idx
            return
        if isinstance(out, tuple):
            # Speculative verify block: ([K, S, Q] candidates, [K, S]
            # accept counts). Per row and iteration, the first count
            # tokens are real (count-1 accepted drafts + 1 corrected or
            # bonus token); the rest were rejected on device. K-major so
            # page pressure is handled in device order, and each token
            # re-checks the row guards — a host-detected stop string at
            # candidate i must discard candidates i+1.. of the SAME row.
            with self._wd("verify"):
                emit = np.asarray(out[0])
                counts = np.asarray(out[1])
            for k in range(emit.shape[0]):
                for row, seq, epoch in snapshot:
                    n = int(counts[k, row])
                    if n <= 0:
                        continue
                    if (
                        seq.finish_reason is not None
                        or seq.rid not in self.scheduler.running
                        or seq.epoch != epoch
                    ):
                        continue
                    self.spec_proposed += self.cfg.spec_tokens
                    self.spec_accepted += n - 1
                    for i in range(n):
                        if (
                            seq.finish_reason is not None
                            or seq.rid not in self.scheduler.running
                            or seq.epoch != epoch
                        ):
                            break
                        self._append_and_check(
                            seq, int(emit[k, row, i]), finished
                        )
            self._processed_idx = idx
            return
        with self._wd("decode_block" if kind == "decode" else "prefill"):
            tokens = np.asarray(out)  # transfer started at dispatch; ~ready
        # Normalise to a [K, rows] block: prefill outputs and K=1 decode
        # steps are 1-D [rows]; fused decode blocks are already [K, S].
        # Iterating k-major reproduces exactly the per-step processing
        # order K=1 had (all rows' token k before any row's token k+1).
        if tokens.ndim == 1:
            tokens = tokens[None]
        for k_tokens in tokens:
            for row, seq, epoch in snapshot:
                if (
                    seq.finish_reason is not None
                    or seq.rid not in self.scheduler.running
                    or seq.epoch != epoch
                ):
                    # Finished, preempted, or preempted-and-readmitted
                    # (epoch mismatch) while this step was in flight —
                    # including rows that finished or self-preempted at
                    # an earlier iteration of this very block: their
                    # remaining in-block tokens are lagged garbage (the
                    # device rode them out inactive) and are discarded.
                    continue
                self._append_and_check(seq, int(k_tokens[row]), finished)
        self._processed_idx = idx

    def _eval_guard(
        self,
        kind: str,
        guard: tuple,
        snapshot: List[Tuple[int, Sequence, int]],
    ) -> None:
        """Fetch one dispatch's on-device guard fold and raise a
        classifiable :class:`LogitGuardError` if any check tripped.

        The fetch rides the same async copy as the tokens (started at
        dispatch), so by drain time it is host-resident. Fused blocks
        ship per-iteration folds [K, ...]; they are combined here —
        trivial host arithmetic on a [K, 3] + [K, S] pair."""
        with self._wd("guard"):
            stats = np.asarray(guard[0])
            bad = np.asarray(guard[1])
        if stats.ndim == 2:  # stacked per-scan-iteration folds
            # Host-side combine of the already-fetched [K, 3] fold (the
            # bracket above did the device fetch) — no device value here.
            stats = np.array(  # llmq: ignore[unguarded-device-fetch]
                [stats[:, 0].sum(), stats[:, 1].max(), stats[:, 2].min()]
            )
        if bad.ndim == 2:
            bad = bad.any(axis=0)
        if not bad.any():
            return
        checks = []
        if stats[0] > 0:
            checks.append("nonfinite")
        if self.guard_logit_max > 0 and stats[1] > self.guard_logit_max:
            checks.append("logit_max")
        if (
            self.guard_entropy_min > 0
            and np.isfinite(stats[2])
            and stats[2] < self.guard_entropy_min
        ):
            checks.append("entropy_collapse")
        suspects = tuple(
            seq.rid
            for row, seq, _epoch in snapshot
            if row < bad.shape[0] and bad[row]
        )
        self.guard_trips += 1
        raise LogitGuardError(
            check="+".join(checks) or "guard",
            detail=(
                f"nonfinite={stats[0]:.0f} max|logit|={stats[1]:.4g} "
                f"min_entropy={stats[2]:.4g} rows={int(bad.sum())}"
            ),
            suspects=suspects,
            kind=kind,
        )

    def _flush_deferred(self) -> None:
        # Swap-to-host captures first: a swap entry shares its watermark
        # with the _deferred_pages entry appended by the same preemption,
        # and its pages must be gathered to host BEFORE they return to
        # the allocator (a reallocated page gets overwritten by the next
        # prefill). At the watermark every in-flight write to these pages
        # has executed — _process_oldest blocked on that step's outputs.
        while (
            self._pending_swaps
            and self._pending_swaps[0][0] <= self._processed_idx
        ):
            _, seq, pages, valid, epoch = self._pending_swaps.pop(0)
            self._capture_swap(seq, pages, valid, epoch)
        while (
            self._deferred_pages
            and self._deferred_pages[0][0] <= self._processed_idx
        ):
            _, pages, cacheable = self._deferred_pages.pop(0)
            self.scheduler.release_pages(pages, cacheable)

    def _capture_swap(
        self, seq: Sequence, pages: List[int], valid: int, epoch: int
    ) -> None:
        """Gather a swap-preempted sequence's KV pages to host RAM, so
        re-admission scatters them back instead of re-prefilling. Skipped
        (falling back to recompute, which is always correct) when the
        sequence moved on while the capture waited for its watermark:
        re-admitted, finished/aborted, preempted again, or already
        carrying a restore."""
        if (
            seq.epoch != epoch
            or seq.finish_reason is not None
            or seq.rid in self.scheduler.running
            or seq.restore is not None
        ):
            return
        n = snapshot_mod.pages_for(valid, self.cfg.page_size)
        if n == 0 or n > len(pages):
            return
        if not self._admit_swap_capture(n):
            return  # recompute fallback: re-admission re-prefills
        # The gather helper blocks until the copies land, so the fresh
        # host buffers are safe against the pools' later donation.
        with self._wd("snapshot_gather"):
            k, v = self._kv_gather_np(pages[:n])
        seq.restore = snapshot_mod.KVRestore(k=k, v=v, valid=valid)
        self.swap_preempts += 1

    def _page_host_bytes(self) -> int:
        """Host bytes one swapped KV page costs (K + V)."""
        if self.pp > 1:
            k_bytes = sum(
                int(kp.size) * int(jnp.dtype(kp.dtype).itemsize)
                for kp in self.k_pages
            )
        else:
            k_bytes = int(self.k_pages.size) * int(
                jnp.dtype(self.k_pages.dtype).itemsize
            )
        return 2 * (k_bytes // max(1, self.scheduler.config.num_pages))

    def _admit_swap_capture(self, n_pages: int) -> bool:
        """Ask the host-memory governor before buffering ``n_pages`` of
        swapped KV. A refusal downgrades the preemption to recompute
        (the pre-swap behavior — always correct, slower to resume)."""
        if get_governor().admit_swap(n_pages * self._page_host_bytes()):
            return True
        self.swap_refused += 1
        return False

    def _swap_restore_bytes(self) -> int:
        """Governor gauge: host bytes currently held by swap/restore KV
        blobs awaiting re-admission."""
        total = 0
        for seq in list(self.scheduler.waiting):
            r = seq.restore
            if r is not None:
                total += int(r.k.nbytes) + int(r.v.nbytes)
        return total

    def _evict_prefix_bytes(self, nbytes: int) -> int:
        """Governor evictor: drop cold prefix entries (oldest first)
        until ``nbytes`` are freed or the store is empty."""
        store = self.prefix_store
        if store is None:
            return 0
        freed = 0
        while freed < nbytes and len(store):
            before = store.occupancy_bytes
            store._evict_one()
            freed += before - store.occupancy_bytes
        return freed

    def _on_scheduler_preempt(self, seq: Sequence, deferred: bool) -> None:
        """Scheduler ``on_preempt`` hook. Deferred self-preemptions queue
        their own watermark capture in ``_self_preempt_deferred``; the
        immediate path (scheduler-picked victim under pool exhaustion,
        only reachable with the pipeline drained) gathers the victim's KV
        here, while it still owns its pages."""
        if (
            deferred
            or self.preempt_mode != "swap"
            or not seq.prefilled
            or not seq.pages
            or seq.restore is not None
        ):
            return
        assert not self._pending, "immediate preempt with in-flight steps"
        valid = seq.num_tokens - 1
        n = snapshot_mod.pages_for(valid, self.cfg.page_size)
        if n == 0 or n > len(seq.pages):
            return
        if not self._admit_swap_capture(n):
            return  # recompute fallback: re-admission re-prefills
        with self._wd("snapshot_gather"):
            k, v = self._kv_gather_np(seq.pages[:n])
        seq.restore = snapshot_mod.KVRestore(k=k, v=v, valid=valid)
        self.swap_preempts += 1

    # --- host prefix tier -------------------------------------------------
    def _demote_page(self, page: int, hashes: List[bytes]) -> None:
        """Scheduler ``on_demote`` hook: park an evicted cache page's KV
        in the host tier, keyed by every chain hash that pointed at it.
        Safe to gather here: a cached page is refcount-0 whose deferred
        release passed the watermark, so every in-flight write to it has
        executed, and the gather reads the newest pool reference (the
        donation chain's live buffer). np.asarray blocks until the copy
        lands, before the page can be reallocated and overwritten."""
        if self.prefix_store is None:
            return
        idx = jnp.asarray([page], jnp.int32)
        with self._wd("snapshot_gather"):
            k = np.asarray(_dispatch.gather_kv_pages(self.k_pages, idx))
            v = np.asarray(_dispatch.gather_kv_pages(self.v_pages, idx))
        for h in hashes:
            self.prefix_store.put(h, k, v)
        self.prefix_demotes += 1

    def _host_prefix_lookup(self, hashes: List[bytes]):
        """Scheduler ``host_lookup`` hook: the longest contiguous run of
        host-tier pages extending a device-cache match."""
        return self.prefix_store.match_chain(hashes)

    def _promote_host_pages(self, admitted: List[Sequence]) -> None:
        """Insert host-tier KV into the pages admit() reserved for it,
        before the wave's first dispatch. No ``_dirty`` resync needed:
        the sequences are still unprefilled (prefill's scatter brings
        their decode rows up), and ``_kv_insert_jit`` donates the pool
        like every other KV write. Also emits the per-request
        ``prefix_hit`` trace event covering device + host reuse."""
        for seq in admitted:
            if seq.prefix_len > 0:
                emit_trace_event(seq.rid, "prefix_hit", tokens=seq.prefix_len)
            hr = seq.host_restore
            if not hr:
                continue
            seq.host_restore = None
            # Host list → numpy; no device value involved.
            idx = np.asarray([page for page, _, _ in hr], np.int32)  # llmq: ignore[unguarded-device-fetch]
            k = np.concatenate([e.k for _, _, e in hr], axis=1)
            v = np.concatenate([e.v for _, _, e in hr], axis=1)
            self._kv_insert_np(idx, k, v)
            self.prefix_promotes += len(hr)

    def flush_prefix_to_host(self) -> int:
        """Demote every evictable (refcount-0) cached page to the host
        tier now, instead of waiting for pool pressure. Used before a
        planned teardown — and by the probes to exercise the
        demote→promote path deterministically. Returns the number of
        pages dropped from the device cache."""
        pages = list(self.scheduler.allocator._cached)
        for page in pages:
            self.scheduler.allocator.drop_cached(page)  # fires on_evict
        return len(pages)

    def export_prefix_chunks(self, digests_hex: List[str]) -> List[str]:
        """Serialize requested prefix pages for a peer (base64 chunk wire
        form). Each digest resolves against the host tier first, then the
        device cache (gathering on demand) — misses are skipped, not
        errors: shipping is best-effort and the requester re-prefills
        whatever doesn't arrive."""
        from llmq_tpu.engine import prefix_store as prefix_mod

        out: List[str] = []
        sig = self._model_sig()
        for hx in digests_hex:
            try:
                key = bytes.fromhex(hx)
            except ValueError:
                continue
            k = v = None
            if self.prefix_store is not None and key in self.prefix_store:
                entry = self.prefix_store.get(key)
                k, v = entry.k, entry.v
            else:
                page = self.scheduler._prefix_cache.get(key)
                if page is not None:
                    with self._wd("snapshot_gather"):
                        k, v = self._kv_gather_np([page])
            if k is None:
                continue
            blob = prefix_mod.chunk_to_bytes(
                key, k, v, model_sig=sig, page_size=self.cfg.page_size
            )
            out.append(prefix_mod.chunk_to_b64(blob))
            self.prefix_chunks_exported += 1
        return out

    def ingest_prefix_chunks(self, chunks_b64: List[str]) -> int:
        """Accept shipped prefix pages into the host tier (they promote
        to device on the next matching admission). Returns the number
        accepted; 0 when the host tier is disabled. Malformed or
        incompatible chunks raise — a fleet where shapes disagree should
        fail loudly, not silently recompute forever."""
        if self.prefix_store is None:
            return 0
        from llmq_tpu.engine import prefix_store as prefix_mod

        n = 0
        sig = self._model_sig()
        for c in chunks_b64:
            key, k, v, chunk_sig, page_size = prefix_mod.chunk_from_bytes(
                prefix_mod.chunk_from_b64(c)
            )
            prefix_mod.check_chunk_compat(
                chunk_sig,
                page_size,
                want_sig=sig,
                want_page_size=self.cfg.page_size,
            )
            if self.prefix_store.put(key, k, v):
                n += 1
                self.prefix_chunks_ingested += 1
        return n

    def missing_prefix_digests(self, digests_hex: List[str]) -> List[str]:
        """Subset of the given chain digests resident in NEITHER the
        device prefix cache nor the host tier — the want-list a worker
        sends to an affinity peer before recomputing a prefix. Pure
        dict/host lookups (no device work, no counter churn)."""
        missing: List[str] = []
        for hx in digests_hex:
            try:
                key = bytes.fromhex(hx)
            except ValueError:
                continue
            if key in self.scheduler._prefix_cache:
                continue
            if self.prefix_store is not None and key in self.prefix_store:
                continue
            missing.append(hx)
        return missing

    def hot_prefix_chains(self, n: int = 8) -> List[str]:
        """Hex digests of this engine's hottest prefix chains — host-tier
        entries by hit count, padded with device-cache chain heads. The
        heartbeat advertises these for affinity routing and shipping."""
        out: List[str] = []
        if self.prefix_store is not None:
            out.extend(self.prefix_store.hot_chains(n))
        if len(out) < n:
            for h in self.scheduler._prefix_cache:
                hx = h.hex()
                if hx not in out:
                    out.append(hx)
                if len(out) >= n:
                    break
        return out

    def _split_guard(self, out):
        """Split a jitted step's token output from its guard fold.

        With the guard on every step returns ``(tokens, (stats, bad))``;
        off, the output is the pre-existing structure and the guard slot
        is ``None`` — callers stay shape-agnostic either way."""
        if self.logit_guard == "on":
            return out
        return out, None

    def _push_pending(
        self,
        kind: str,
        out: jax.Array,
        snapshot: List[Tuple[int, Sequence]],
        guard: Optional[tuple] = None,
    ) -> None:
        arrs = list(out) if isinstance(out, tuple) else [out]
        if guard is not None:
            arrs.extend(guard)
        for arr in arrs:
            try:
                arr.copy_to_host_async()
            except Exception:  # noqa: BLE001 — numpy leaves / no support
                pass
        self._dispatch_idx += 1
        if kind in ("decode", "mixed"):
            self._pending_decodes += 1
        # Stamp each row with its sequence's preemption epoch: a row
        # snapshotted before a self-preemption must not be appended after
        # the sequence is re-admitted (its token came from abandoned
        # device state).
        stamped = [(row, seq, seq.epoch) for row, seq in snapshot]
        self._pending.append((self._dispatch_idx, kind, out, stamped, guard))

    def _resync(self) -> None:
        """Rebuild the device decode state from scheduler truth. Only valid
        after a full drain (host state must have caught up)."""
        assert not self._pending, "resync with in-flight steps"
        fills = [
            (self._h_tokens, 0), (self._h_ctx, 0), (self._h_active, False),
            (self._h_bt, 0), (self._h_temp, 0.0), (self._h_topk, 0),
            (self._h_topp, 1.0), (self._h_keys, 0), (self._h_steps, 0),
            (self._h_limits, 0), (self._h_mins, 0), (self._h_stopids, -1),
        ]
        if self._h_history is not None:
            fills.append((self._h_history, 0))
        for arr, fill in fills:
            arr[...] = fill
        modes = []
        for i, seq in enumerate(self.scheduler.slots):
            if seq is None or not seq.prefilled:
                continue  # unprefilled slots join via the prefill scatter
            p = seq.params
            self._h_tokens[i] = seq.last_token
            self._h_ctx[i] = seq.num_tokens - 1
            self._h_bt[i, : len(seq.pages)] = seq.pages
            self._h_active[i] = True
            self._h_temp[i] = p.temperature
            self._h_topk[i] = p.top_k
            self._h_topp[i] = p.top_p
            # µs-scale PRNG-key fetch at admission, not a dispatch wait.
            self._h_keys[i] = np.asarray(make_base_key(p.seed, request_tag(seq.rid)))  # llmq: ignore[unguarded-device-fetch]
            self._h_steps[i] = len(seq.output_ids)
            self._h_limits[i] = p.max_tokens
            self._h_mins[i] = p.min_tokens
            self._h_stopids[i] = self._stop_ids_for(seq)
            if self._h_history is not None:
                ids = seq.prompt_ids + seq.output_ids
                self._h_history[i, : len(ids)] = ids
            modes.append(sampling_mod.required_mode(p))
        self._mode = sampling_mod.join_modes(modes) if modes else "greedy"
        # One batched transfer with the final shardings — no per-array
        # convert programs, no resharding on first dispatch.
        state = (
            self._h_tokens, self._h_ctx, self._h_bt, self._h_active,
            self._h_keys, self._h_steps, self._h_temp, self._h_topk,
            self._h_topp, self._h_limits, self._h_mins, self._h_stopids,
        )
        if self._h_history is not None:
            state += (self._h_history,)
        self._dev_state = jax.device_put(state, self._st_shardings)
        self._dirty = False

    def _grow_stop_capacity(self, need: int) -> None:
        """Widen the per-slot stop-id arrays to the next power of two
        >= ``need``. The device decode-state shape changes, so the state
        is marked dirty (next dispatch drains in-flight steps and resyncs
        at the new shape; jit retraces once). Grow-only — a rare wide
        request costs one recompile, never a truncated stop set. The live
        capacity is engine state (``_stop_capacity``), not a mutation of
        the caller's EngineConfig (which may be shared across cores)."""
        E = 1 << max(need - 1, 1).bit_length()
        self._stop_capacity = E
        S = self.cfg.max_num_seqs
        self._h_stopids = np.full((S, E), -1, np.int32)
        self._dirty = True

    def _stop_ids_for(self, seq: Sequence) -> np.ndarray:
        """Per-slot device stop-token ids ([-1]-padded). Capacity has
        already been grown by ``add_request``, so the set always fits."""
        E = self._stop_capacity
        ids = list(dict.fromkeys(seq.params.stop_token_ids))
        if not seq.params.ignore_eos:
            ids.extend(i for i in self._eos_ids if i not in ids)
        assert len(ids) <= E, f"stop set {len(ids)} > capacity {E}"
        row = np.full((E,), -1, np.int32)
        row[: len(ids)] = ids
        return row

    # --- prefill ----------------------------------------------------------
    def _prefill_batch(
        self, seqs: List[Sequence], finished: List[RequestOutput]
    ) -> None:
        """Prefill admitted sequences in bucket-grouped batches; the
        compiled step scatters each row straight into the device decode
        state, so admission costs no pipeline drain."""
        if self._dirty:
            self._drain(finished)
            self._resync()
        if self.cfg.prefill_chunk_size:
            if self.mixed_step == "on":
                self._prefill_mixed(seqs, finished)
            else:
                self._prefill_chunked(seqs, finished)
            return
        by_bucket: Dict[int, List[Sequence]] = {}
        for seq in seqs:
            n = seq.num_tokens
            bucket = next(b for b in self._buckets if b >= n)
            by_bucket.setdefault(bucket, []).append(seq)
        # Decode interleaving across a multi-chunk wave happens at the
        # step() level (one decode per _try_admit round); per-chunk
        # interleaving inside one call only matters for the chunked path,
        # where a single long prompt spans many dispatches.
        for bucket, group in by_bucket.items():
            for i in range(0, len(group), self.cfg.max_prefill_batch):
                self._prefill_chunk(group[i : i + self.cfg.max_prefill_batch],
                                    bucket)

    def _prefill_chunked(
        self, seqs: List[Sequence], finished: List[RequestOutput]
    ) -> None:
        """Chunked prefill: run each admitted group's prompts through the
        single fixed-[B, C] chunk executable, C positions at a time, and
        interleave one decode step for the already-running batch between
        chunks — a long prompt costs the decoders ceil(len/C) short
        stalls instead of one long one."""
        C = self.cfg.prefill_chunk_size
        B = self.cfg.max_prefill_batch
        repl = self._repl
        # Interleave decode only for sequences decodable BEFORE this
        # wave: a cold-start wave interleaving its own fresh rows would
        # pay full-cost decode steps at tiny occupancy — the waste wave
        # admission exists to avoid.
        pre_wave = [s.rid for s in self._decodable_seqs()]
        for i in range(0, len(seqs), B):
            rows = seqs[i : i + B]
            # Snapshot every chunk-invariant per-row value ONCE, and ship
            # the invariant arrays to the device ONCE per group. The live
            # seq.num_tokens/output_ids MUST NOT be re-read inside the lo
            # loop: interleaved decode steps append tokens to rows that
            # went final in an earlier chunk, and a re-read length would
            # mark such a row "final" again — double-scattering it and
            # rewinding its device RNG/step state. (Block tables are the
            # one exception below: pages only grow, and the final-chunk
            # scatter should carry the freshest map.)
            lens = [seq.num_tokens for seq in rows]
            ids0 = [seq.prompt_ids + seq.output_ids for seq in rows]
            # Prefix-cached positions are already in the (shared) leading
            # pages — each row prefills from its own prefix_len on.
            prefix0 = [seq.prefix_len for seq in rows]
            lengths0 = np.zeros((B,), np.int32)
            lengths0[: len(rows)] = lens
            inv_arrays = (lengths0, *self._pack_sampling_rows(rows, B))
            if self.cfg.spec_tokens > 0:
                inv_arrays += (self._pack_history_rows(rows, B),)
            inv = jax.device_put(inv_arrays, (repl,) * len(inv_arrays))
            chunk_mode = sampling_mod.join_modes(
                sampling_mod.required_mode(s.params) for s in rows
            )
            maxlen = max(lens)
            for lo in range(0, maxlen, C):
                tokens = np.zeros((B, C), np.int32)
                positions = np.full((B, C), -1, np.int32)
                bt = np.zeros((B, self._pages_per_seq), np.int32)
                final = np.zeros((B,), bool)
                last = np.zeros((B,), np.int32)
                snapshot: List[Tuple[int, Sequence]] = []
                any_rows = False
                for r, seq in enumerate(rows):
                    n = lens[r]
                    hi = min(n, lo + C)
                    row_start = max(lo, prefix0[r])
                    if (
                        lo >= n
                        or hi <= prefix0[r]  # still inside the cached prefix
                        or seq.rid not in self.scheduler.running
                    ):
                        continue  # nothing to compute — padding row
                    any_rows = True
                    self.prefill_tokens += hi - row_start
                    tokens[r, : hi - row_start] = ids0[r][row_start:hi]
                    positions[r, : hi - row_start] = np.arange(row_start, hi)
                    bt[r, : len(seq.pages)] = seq.pages  # live: grow-only
                    if row_start <= n - 1 < hi:
                        final[r] = True
                        last[r] = n - 1 - row_start
                        snapshot.append((r, seq))
                if not any_rows:
                    continue  # whole chunk inside every row's prefix
                chunk_args = jax.device_put(
                    (tokens, positions, bt, final, last), (repl,) * 5
                )
                t0 = time.monotonic()
                for seq in rows:
                    if seq.t_prefill_start == 0.0:
                        seq.t_prefill_start = t0
                with self._wd("prefill"):
                    out, self.k_pages, self.v_pages, self._dev_state = (
                        self._chunkfill_jits[chunk_mode](
                            self.params, self.k_pages, self.v_pages,
                            *chunk_args, *inv, self._dev_state,
                        )
                    )
                    self._record_dispatch("prefill", time.monotonic() - t0)
                out, g = self._split_guard(out)
                if snapshot:  # rows whose prompt finished in this chunk
                    for _, seq in snapshot:
                        seq.prefilled = True
                        self.scheduler.register_prefix(seq)
                    self.prefills += len(snapshot)
                    self._push_pending("prefill", out, snapshot, g)
                    self._mode = sampling_mod.join_modes(
                        (self._mode, chunk_mode)
                    )
                elif g is not None:
                    # No row finished in this chunk, but the guard fold
                    # still needs its drain-time verdict: ride the
                    # pipeline with an empty row snapshot.
                    self._push_pending("prefill", out, [], g)
                # Interleave: let pre-wave sequences advance while the
                # next chunk queues behind this one on the device stream
                # (an idle engine's long first prompt must not pay an
                # empty decode step per chunk, and a cold-start wave must
                # not decode its own fresh rows at tiny occupancy).
                if lo + C < maxlen and any(
                    rid in self.scheduler.running for rid in pre_wave
                ):
                    self._dispatch_decode(finished)

    def _prefill_mixed(
        self, seqs: List[Sequence], finished: List[RequestOutput]
    ) -> None:
        """Piggyback scheduling driver: prefill each admitted sequence by
        fusing its chunk segments INTO the decode dispatches instead of
        alternating whole dispatches. Every mixed dispatch advances the
        running batch by ``decode_block`` tokens (exactly like
        ``_dispatch_decode``) while the piggy's prompt trickles in under
        the per-iteration token budget (``mixed_token_budget``): the
        decode batch never stalls for a prefill, and the prefill rides
        compute the decode step was leaving idle. One sequence
        piggybacks at a time; when its final segment lands before the
        last iteration of a dispatch, the remaining iterations decode it
        in-dispatch (pages for those positions are ensured up front —
        under pool pressure the plan falls back to finishing at the last
        iteration, which needs none)."""
        C = self.cfg.prefill_chunk_size
        K = self.cfg.decode_block
        repl = self._repl
        for seq in seqs:
            # The fusion only pays when a decode batch is riding along:
            # with nothing decodable a mixed dispatch is chunked prefill
            # with S-1 wasted rows — use the plain chunk loop.
            if not self._decodable_seqs():
                self._prefill_chunked([seq], finished)
                continue
            epoch0 = seq.epoch
            # Snapshot chunk-invariant values ONCE (the same discipline
            # as _prefill_chunked): mixed dispatches append tokens to
            # OTHER rows, never to the mid-prefill piggy.
            n = seq.num_tokens
            ids0 = seq.prompt_ids + seq.output_ids
            cur = seq.prefix_len  # cached prefix pages already hold KV
            seq_mode = sampling_mod.required_mode(seq.params)
            inv_arrays = (
                # Host int → numpy; no device value involved.
                np.asarray([n], np.int32),  # llmq: ignore[unguarded-device-fetch]
                *self._pack_sampling_rows([seq], 1),
            )
            if self.cfg.spec_tokens > 0:
                inv_arrays += (self._pack_history_rows([seq], 1),)
            inv = jax.device_put(inv_arrays, (repl,) * len(inv_arrays))
            while cur < n:
                if (
                    seq.rid not in self.scheduler.running
                    or seq.epoch != epoch0
                ):
                    break  # preempted mid-prefill; re-admission restarts
                # Plan this dispatch's K segments under the token budget
                # (decode rows first, remainder to the piggy's prompt).
                decode_rows = len(self._decodable_seqs())
                segs: List[Tuple[int, int]] = []
                pos, final_k = cur, None
                for k in range(K):
                    take = mixed_token_budget(C, decode_rows, n - pos)
                    segs.append((pos, take))
                    pos += take
                    if take and pos >= n:
                        final_k = k
                if final_k is not None and final_k < K - 1:
                    # The iterations after activation decode the piggy
                    # in-dispatch, writing positions n..n+K-2-final_k —
                    # their pages must exist BEFORE the dispatch.
                    extra = K - 1 - final_k
                    try:
                        self.scheduler.ensure_pages(
                            seq,
                            self._page_target(seq, extra),
                            allow_preempt=False,
                        )
                    except OutOfPages:
                        self._drain(finished)
                        self._flush_deferred()
                        try:
                            self.scheduler.ensure_pages(
                                seq,
                                self._page_target(seq, extra),
                                allow_preempt=False,
                            )
                        except OutOfPages:
                            # Re-plan: the final segment moves to the
                            # LAST iteration (empty middles become pure
                            # decode iterations) — no in-dispatch piggy
                            # decode, no extra pages.
                            start, take = segs[final_k]
                            for k in range(final_k, K - 1):
                                segs[k] = (start, 0)
                            segs[K - 1] = (start, take)
                            final_k = K - 1
                # Decode rows' own page lookahead + dirty resync — the
                # mixed dispatch IS their decode dispatch.
                if not self._ensure_decode_pages(finished):
                    break  # piggy itself left running (preempt/abort)
                if (
                    seq.rid not in self.scheduler.running
                    or seq.epoch != epoch0
                ):
                    break
                m_tokens = np.zeros((K, C), np.int32)
                m_positions = np.full((K, C), -1, np.int32)
                m_final = np.zeros((K,), bool)
                m_last = np.zeros((K,), np.int32)
                for k, (start, take) in enumerate(segs):
                    if take:
                        m_tokens[k, :take] = ids0[start : start + take]
                        m_positions[k, :take] = np.arange(start, start + take)
                if final_k is not None:
                    m_final[final_k] = True
                    m_last[final_k] = n - 1 - segs[final_k][0]
                m_bt = np.zeros((1, self._pages_per_seq), np.int32)
                m_bt[0, : len(seq.pages)] = seq.pages  # live: grow-only
                seg_args = jax.device_put(
                    (m_tokens, m_positions, m_final, m_last, m_bt),
                    (repl,) * 5,
                )
                # The executable must cover the piggy's sampler needs as
                # well as the batch's (its first token samples here).
                mode = sampling_mod.join_modes((self._mode, seq_mode))
                t0 = time.monotonic()
                if seq.t_prefill_start == 0.0:
                    seq.t_prefill_start = t0
                with self._wd("mixed"):
                    out, self.k_pages, self.v_pages, self._dev_state = (
                        self._mixedfill_jits[mode](
                            self.params, self.k_pages, self.v_pages,
                            *seg_args, *inv, self._dev_state,
                        )
                    )
                    self._record_dispatch("mixed", time.monotonic() - t0)
                self.mixed_steps += 1
                self.mixed_prefill_tokens += sum(t for _, t in segs)
                self.prefill_tokens += sum(t for _, t in segs)
                self.decode_steps += K
                self.decode_dispatches += 1
                if final_k is not None:
                    seq.prefilled = True
                    self.scheduler.register_prefix(seq)
                    self.prefills += 1
                    self._mode = mode
                # Snapshot AFTER marking prefilled so the piggy's row is
                # included; its tokens before final_k are skipped via
                # the per-row start index.
                starts = np.zeros((self.cfg.max_num_seqs,), np.int32)
                if final_k is not None:
                    starts[seq.slot] = final_k
                out, g = self._split_guard(out)
                self._push_pending(
                    "mixed",
                    (out, starts),
                    [
                        (i, s)
                        for i, s in enumerate(self.scheduler.slots)
                        if s is not None and s.prefilled
                    ],
                    g,
                )
                while len(self._pending) > self.cfg.runahead:
                    self._process_oldest(finished)
                cur = pos

    def _pack_sampling_rows(self, rows: List[Sequence], B: int) -> tuple:
        """Per-row device-state arrays shared by both prefill paths
        (bucketed + chunked): slots, RNG keys, step counts, sampling
        params, stop-id rows. Padding rows keep slot −1 / limit 1."""
        E = self._stop_capacity
        key_shape = self._h_keys.shape[1:]
        slots = np.full((B,), -1, np.int32)
        keys = np.zeros((B, *key_shape), np.uint32)
        steps = np.zeros((B,), np.int32)
        temps = np.zeros((B,), np.float32)
        topks = np.zeros((B,), np.int32)
        topps = np.ones((B,), np.float32)
        limits = np.full((B,), 1, np.int32)
        mins = np.zeros((B,), np.int32)
        stopids = np.full((B, E), -1, np.int32)
        for r, seq in enumerate(rows):
            p = seq.params
            slots[r] = seq.slot
            # µs-scale PRNG-key fetch while packing, not a dispatch wait.
            keys[r] = np.asarray(make_base_key(p.seed, request_tag(seq.rid)))  # llmq: ignore[unguarded-device-fetch]
            steps[r] = len(seq.output_ids)
            temps[r] = p.temperature
            topks[r] = p.top_k
            topps[r] = p.top_p
            limits[r] = p.max_tokens
            mins[r] = p.min_tokens
            stopids[r] = self._stop_ids_for(seq)
        return slots, keys, steps, temps, topks, topps, limits, mins, stopids

    def _pack_history_rows(self, rows: List[Sequence], B: int) -> np.ndarray:
        """Per-row prompt+output token history for the speculative
        drafter ([B, max_model_len], zero-padded): the prefill scatter
        installs it as the row's device-side lookup corpus."""
        hist = np.zeros((B, self.cfg.max_model_len), np.int32)
        for r, seq in enumerate(rows):
            ids = seq.prompt_ids + seq.output_ids
            hist[r, : len(ids)] = ids
        return hist

    def _prefill_chunk(self, chunk: List[Sequence], bucket: int) -> None:
        # Pad to {1, max_prefill_batch} rows so at most two executables
        # exist per bucket.
        B = 1 if len(chunk) == 1 else self.cfg.max_prefill_batch
        tokens = np.zeros((B, bucket), np.int32)
        lengths = np.zeros((B,), np.int32)
        bt = np.zeros((B, self._pages_per_seq), np.int32)
        for row, seq in enumerate(chunk):
            ids = seq.prompt_ids + seq.output_ids
            tokens[row, : len(ids)] = ids
            lengths[row] = len(ids)
            bt[row, : len(seq.pages)] = seq.pages
        arg_arrays = (tokens, lengths, bt, *self._pack_sampling_rows(chunk, B))
        if self.cfg.spec_tokens > 0:
            arg_arrays += (self._pack_history_rows(chunk, B),)
        args = jax.device_put(arg_arrays, self._prefill_arg_shardings)
        chunk_mode = sampling_mod.join_modes(
            sampling_mod.required_mode(s.params) for s in chunk
        )
        t0 = time.monotonic()
        for seq in chunk:
            if seq.t_prefill_start == 0.0:
                seq.t_prefill_start = t0
        with self._wd("prefill"):
            out, self.k_pages, self.v_pages, self._dev_state = (
                self._prefill_jits[chunk_mode](
                    self.params, self.k_pages, self.v_pages, *args,
                    self._dev_state,
                )
            )
            self._record_dispatch("prefill", time.monotonic() - t0)
        for seq in chunk:
            seq.prefilled = True
            self.prefill_tokens += seq.num_tokens
        self.prefills += len(chunk)
        out, g = self._split_guard(out)
        self._push_pending("prefill", out, list(enumerate(chunk)), g)
        # The new rows' sampler mode must be honored from the next decode.
        self._mode = sampling_mod.join_modes((self._mode, chunk_mode))

    # --- decode -----------------------------------------------------------
    def _ensure_decode_pages(self, finished: List[RequestOutput]) -> bool:
        """Pre-dispatch preamble shared by plain decode and mixed
        (decode + piggybacked prefill) dispatches: page lookahead for
        every decodable row, then the dirty drain + resync. Returns
        False when nothing is left running (caller skips the dispatch).
        """
        # Page lookahead: every position an in-flight or about-to-dispatch
        # step may write must be covered *now* — pages only ever get
        # *added* to a block table, so the grown table can be swapped into
        # the device state without draining the pipeline (in-flight steps
        # only touch already-mapped positions). Demand is capped by each
        # sequence's own remaining generation budget. Only allocator
        # exhaustion (preemption needed) forces a drain + resync.
        # Count only in-flight DECODE entries: a pending prefill writes
        # solely its own new rows, so a wave of refill chunks must not
        # inflate every running sequence's page demand. Mid-prefill
        # sequences are excluded outright: their prompt pages were fully
        # allocated at admission, decode steps never write their rows,
        # and demanding lookahead pages for them here could cascade into
        # preempting/length-finishing a row whose chunk loop is still in
        # flight (zombie-slot corruption).
        # Each in-flight decode entry covers decode_block positions —
        # times spec_tokens+1 when speculating, since every verify
        # iteration writes KV for ALL candidate positions (accepted or
        # not) — and the dispatch below adds another block; +1 slack.
        # (K=1, spec off recovers the historical `pending + 2`.)
        K = self.cfg.decode_block
        lookahead = (
            (self._pending_decodes + 1) * K * (self.cfg.spec_tokens + 1) + 1
        )
        decodable = self._decodable_seqs()
        needs_pages = any(
            -(-self._page_target(seq, lookahead) // self.cfg.page_size)
            > len(seq.pages)
            for seq in decodable
        )
        if needs_pages:
            grown = False
            for seq in decodable:
                if seq.rid not in self.scheduler.running:
                    continue  # preempted by an earlier iteration's ensure
                try:
                    # Amortize: top up a full page beyond the need — but
                    # never at someone else's expense.
                    before = len(seq.pages)
                    self.scheduler.ensure_pages(
                        seq,
                        self._page_target(
                            seq, lookahead + self.cfg.page_size
                        ),
                        allow_preempt=False,
                    )
                    grown = grown or len(seq.pages) > before
                except OutOfPages:
                    # Pool exhausted: catch the host up so deferred pages
                    # return and preemption can free a victim safely.
                    self._drain(finished)
                    if seq.rid not in self.scheduler.running:
                        continue
                    try:  # minimal demand; preemption allowed (drained) —
                        # but never of a mid-prefill sequence, whose
                        # in-flight chunk loop would keep writing its old
                        # (freed) pages.
                        self.scheduler.ensure_pages(
                            seq,
                            self._page_target(seq, lookahead),
                            preemptible=lambda s: s.prefilled,
                        )
                    except OutOfPages:
                        if len(self.scheduler.running) == 1:
                            # Truly alone and still short: the pool
                            # itself is the cap. Must go through
                            # _finish_seq: pages stay deferred while
                            # in-flight steps may write them, and the
                            # dirty resync deactivates the device slot (a
                            # zombie slot would keep scattering KV through
                            # its stale block table into reallocated
                            # pages).
                            self._finish_seq(seq, "length",
                                             device_detected=False,
                                             finished=finished)
                        else:
                            # Others hold the pool (e.g. only mid-prefill
                            # rows, which are never preemption victims):
                            # self-preempt instead of truncating — the
                            # request retries once pages free (vLLM
                            # recompute-preemption parity), keeping its
                            # generated tokens. Pages defer like a finish
                            # (in-flight steps may still write them) and
                            # the dirty resync deactivates the slot.
                            self._self_preempt_deferred(seq)
                        continue
                    self._dirty = True
            if grown and not self._dirty:
                self._swap_block_tables()
        if self._dirty:
            self._drain(finished)
            if not self.scheduler.running:
                return False
            self._resync()
        return True

    def _record_dispatch(self, kind: str, seconds: float) -> None:
        """Record the host wall-time of one device dispatch call into the
        per-kind ring buffer + histogram. Dispatch is asynchronous, so
        this measures the host-side launch cost, not device execution —
        spikes mean the host blocked on the device (pipeline stalls)."""
        ring = self._dispatch_rings.get(kind)
        if ring is None:
            ring = self._dispatch_rings[kind] = deque(maxlen=256)
            hist = Histogram(
                "llmq_dispatch_seconds",
                "Host wall-time of one device dispatch call",
                labels={"kind": kind},
            )
            self._dispatch_hists[kind] = hist
            get_registry().register(hist)
        ring.append(seconds)
        self._dispatch_hists[kind].observe(seconds)
        if self.on_dispatch is not None:
            self.on_dispatch(kind)

    def _dispatch_decode(self, finished: List[RequestOutput]) -> None:
        if not self._ensure_decode_pages(finished):
            return
        t0 = time.monotonic()
        kind = "verify" if self.cfg.spec_tokens > 0 else "decode_block"
        jits, k_steps = self._decode_jits, self.cfg.decode_block
        if self._decode_jits_small is not None and any(
            seq.prefilled and seq.priority == "interactive"
            for seq in self.scheduler.running.values()
        ):
            # An interactive row is resident: dispatch the small-K
            # executable so its tokens reach the host (and the stream)
            # every interactive_decode_block iterations instead of every
            # decode_block. Pure-batch steps keep the big fused K.
            jits, k_steps = self._decode_jits_small, self.interactive_decode_block
            kind += "_small"
        with self._wd(kind):
            out, self.k_pages, self.v_pages, self._dev_state = (
                jits[self._mode](
                    self.params, self.k_pages, self.v_pages, self._dev_state
                )
            )
            self._record_dispatch(kind, time.monotonic() - t0)
        self.decode_steps += k_steps
        self.decode_dispatches += 1
        out, g = self._split_guard(out)
        self._push_pending(
            "decode",
            out,
            [
                (i, seq)
                for i, seq in enumerate(self.scheduler.slots)
                if seq is not None and seq.prefilled
            ],
            g,
        )
        while len(self._pending) > self.cfg.runahead:
            self._process_oldest(finished)

    def _self_preempt_deferred(self, seq: Sequence) -> None:
        """Preempt ``seq`` itself with finish-style page deferral: its
        pages return to the allocator only after every in-flight step
        that may write them has been processed. Generated tokens are
        kept; re-admission re-prefills prompt+output. The epoch bump in
        ``Scheduler.preempt`` keeps stale in-flight results (snapshotted
        before the preemption) from being appended after re-admission.

        In swap mode (``LLMQ_PREEMPT_MODE=swap`` / ``preempt_mode``) the
        victim's KV pages are queued for a host gather at the same
        deferred-release watermark, and re-admission scatters them back
        (bit-identical) instead of re-prefilling."""
        swap = (
            self.preempt_mode == "swap" and seq.prefilled and bool(seq.pages)
        )
        pages_copy = list(seq.pages) if swap else None
        kv_valid = seq.num_tokens - 1
        pages, cacheable = self.scheduler.preempt(seq, defer_pages=True)
        if swap:
            # Epoch AFTER the bump: the capture must only fire for this
            # exact preemption, not a later one of the same sequence.
            self._pending_swaps.append(
                (self._dispatch_idx, seq, pages_copy, kv_valid, seq.epoch)
            )
        if pages:
            self._deferred_pages.append(
                (self._dispatch_idx, pages, cacheable)
            )
        self._dirty = True

    def _swap_block_tables(self) -> None:
        """Ship grown block tables into the device state without draining:
        one small h2d transfer, no dispatch, no resync."""
        self._h_bt[...] = 0
        for i, seq in enumerate(self.scheduler.slots):
            if seq is not None:
                self._h_bt[i, : len(seq.pages)] = seq.pages
        bt_dev = jax.device_put(self._h_bt, self._st_shardings[2])
        st = self._dev_state
        self._dev_state = st[:2] + (bt_dev,) + st[3:]

    def _page_target(self, seq: Sequence, lookahead: int) -> int:
        """KV positions ``seq`` must have pages for, given ``lookahead``
        in-flight/future steps — capped by its own finish horizon AND the
        per-sequence page-map capacity (otherwise a full-budget sequence
        would look perpetually short and churn block-table swaps)."""
        horizon = len(seq.prompt_ids) + seq.params.max_tokens + 1
        cap = self._pages_per_seq * self.cfg.page_size
        return min(seq.num_tokens + lookahead, horizon, cap)

    def _append_and_check(
        self, seq: Sequence, token: int, finished: List[RequestOutput]
    ) -> None:
        if seq.prefill_only and not seq.output_ids:
            # Disaggregated prefill boundary: the prompt KV is complete and
            # the device just sampled the first token. Discard the token
            # (the adopting decode worker re-derives the key chain and
            # re-samples it bit-identically), snapshot the prompt KV while
            # the pages are still held, and finish. The snapshot's
            # kv_valid = len(prompt)-1 matches insert_request's contract
            # for an empty-output snapshot, so the decode side recomputes
            # only the last prompt position.
            self._prefill_snapshots[seq.rid] = self._snapshot_seq(seq)
            self.prefill_done += 1
            self._finish_seq(
                seq, "prefill_done", device_detected=False, finished=finished
            )
            return
        seq.output_ids.append(token)
        self.total_generated_tokens += 1
        interactive = seq.priority == "interactive"
        self.class_tokens["interactive" if interactive else "batch"] += 1
        now = time.monotonic()
        if seq.t_first_token == 0.0:
            seq.t_first_token = now
            if seq.t_enqueue > 0.0:
                self.ttft_hist.observe(now - seq.t_enqueue)
                if interactive:
                    self.ttft_hist_interactive.observe(now - seq.t_enqueue)
        elif seq.t_last_token > 0.0:
            # Host-boundary gap: tokens of one fused decode block arrive
            # in a burst, so sub-ms gaps are expected there (the
            # fine-grained ITL_BUCKETS low end exists for exactly this).
            self.itl_hist.observe(now - seq.t_last_token)
            if interactive:
                self.itl_hist_interactive.observe(now - seq.t_last_token)
        seq.t_last_token = now
        # Stops are checked BEFORE the page top-up: a stopping sequence
        # needs no more pages, and the pool-pressure retry below must not
        # swallow a stop/budget finish (a preempted-at-budget row would
        # re-prefill and sample one token past max_tokens).
        n_before = len(seq.output_ids)
        reason = self._stop_reason(seq, token)
        if reason is not None:
            # The token survived the stop check iff it is still in the
            # output (length finishes keep it; stop tokens were popped;
            # stop-string hits pre-truncate text, so nothing streams).
            if (
                self.on_token is not None
                and len(seq.output_ids) == n_before
                and seq.finish_text is None
            ):
                self.on_token(seq, token)
            # The device detects token-based stops and length caps itself
            # (advance_state); only host-exclusive finishes (stop strings)
            # force a resync.
            device_detected = seq.finish_text is None
            self._finish_seq(seq, reason, device_detected=device_detected,
                             finished=finished)
            return
        if self.on_token is not None:
            self.on_token(seq, token)
        try:
            # Pages were pre-allocated at dispatch time; this is a no-op
            # except under pool exhaustion (no preemption here — in-flight
            # steps forbid freeing a victim's pages).
            self.scheduler.ensure_pages(
                seq, seq.num_tokens + 1, allow_preempt=False
            )
        except OutOfPages:
            # Release anything already past the watermark, then retry —
            # an earlier finish/self-preempt in this very drain may have
            # deferred exactly the pages we need.
            self._flush_deferred()
            try:
                self.scheduler.ensure_pages(
                    seq, seq.num_tokens + 1, allow_preempt=False
                )
                return
            except OutOfPages:
                pass
            if (
                len(self.scheduler.running) == 1
                and not self._deferred_pages
            ):
                # Truly alone with nothing pending release: the pool is
                # the cap and retrying would replay to this exact point
                # forever — truncate.
                self._finish_seq(seq, "length", device_detected=False,
                                 finished=finished)
            else:
                # Others hold the pool (or deferred pages will free it):
                # retry later instead of truncating (recompute
                # preemption) — generated tokens are kept.
                self._self_preempt_deferred(seq)

    def _finish_seq(
        self,
        seq: Sequence,
        reason: str,
        *,
        device_detected: bool,
        finished: List[RequestOutput],
    ) -> None:
        pages, cacheable = self.scheduler.finish(seq, reason, defer_pages=True)
        if pages:
            self._deferred_pages.append((self._dispatch_idx, pages, cacheable))
        if not device_detected:
            self._dirty = True
        finished.append(self._output_for(seq))

    def _stop_reason(self, seq: Sequence, token: int) -> Optional[str]:
        p = seq.params
        # Token-based stops are popped from the output, so the surviving
        # output must still hold min_tokens afterwards (strict compare).
        past_min_tok = len(seq.output_ids) > p.min_tokens
        past_min = len(seq.output_ids) >= p.min_tokens
        if past_min_tok and token in p.stop_token_ids:
            seq.output_ids.pop()  # stop token excluded from output
            return "stop"
        if past_min_tok and not p.ignore_eos and token in self._eos_ids:
            seq.output_ids.pop()
            return "stop"
        if len(seq.output_ids) >= p.max_tokens:
            return "length"
        if p.stop and past_min:
            # Incremental detokenization: the decoded head is cached per
            # sequence (Sequence.detok_text covers output_ids[:detok_len])
            # and only the tail past it is decoded each token — the cache
            # trails the end by at least `window` tokens (a stop string
            # spans at most its char count in tokens, +8 slack for
            # multi-char tokens), so a match can never hide entirely
            # inside the frozen head. Before the cache, every token paid
            # a window re-decode and a match paid O(output) full decodes.
            window = max(len(s) for s in p.stop) + 8
            tail = self._detok_tail(seq, window)
            # Only chars that can span the head/tail seam plus the fresh
            # tail need searching; the cached head was already searched
            # when its chars were in the tail of an earlier check.
            seam = max(len(s) for s in p.stop) - 1
            hay = seq.detok_text[-seam:] + tail if seam > 0 else tail
            if any(s in hay for s in p.stop):
                text = seq.detok_text + tail
                hits = [i for i in (text.find(s) for s in p.stop) if i >= 0]
                if hits:
                    idx = min(hits)  # earliest match, not list order
                    seq.finish_text = text[:idx]
                    self._trim_to_match(seq, p.stop)
                    return "stop"
        return None

    def _detok_tail(self, seq: Sequence, window: int) -> str:
        """Text of ``output_ids[detok_len:]``, advancing the cached head
        so it stays exactly ``window`` tokens behind the end (never
        fewer: late tokens could complete a stop string that starts in
        the margin, and BPE detokenization of a token range is only
        seam-stable a safe distance from the end)."""
        n = len(seq.output_ids)
        if seq.detok_len > n:  # output was truncated past the cache
            seq.detok_len, seq.detok_text = 0, ""
        if n - seq.detok_len > window:
            m = n - window
            seq.detok_text += self.tokenizer.decode(
                seq.output_ids[seq.detok_len : m]
            )
            seq.detok_len = m
        return self.tokenizer.decode(seq.output_ids[seq.detok_len :])

    def _trim_to_match(self, seq: Sequence, stops) -> None:
        """Drop output tokens past the stop-string match so token_ids and
        usage agree with the truncated text (bounded: only tokens past
        the cached head can ever be trimmed, and only their tail text is
        re-decoded)."""
        seam = max(len(s) for s in stops) - 1
        head_tail = seq.detok_text[-seam:] if seam > 0 else ""
        lo = seq.detok_len
        for n in range(lo, len(seq.output_ids) + 1):
            head = head_tail + self.tokenizer.decode(seq.output_ids[lo:n])
            if any(s in head for s in stops):
                seq.output_ids = seq.output_ids[:n]
                return

    def _output_for(self, seq: Sequence) -> RequestOutput:
        # Goodput accounting: a clean finish delivered useful work; a
        # shed/expired/cancelled one did not (its tokens were wasted).
        if (seq.finish_reason or "stop") in ("stop", "length"):
            self.class_finished[
                "interactive" if seq.priority == "interactive" else "batch"
            ] += 1
        text = seq.finish_text
        if text is None:
            text = self.tokenizer.decode(seq.output_ids)
        timing: Optional[Dict[str, float]] = None
        if seq.t_enqueue > 0.0:
            timing = {
                "enqueued": seq.t_enqueue,
                "admitted": seq.t_admit,
                "prefill_start": seq.t_prefill_start,
                "first_token": seq.t_first_token,
                "last_token": seq.t_last_token,
                "finished": time.monotonic(),
                "preempt_count": float(seq.preempt_count),
            }
        return RequestOutput(
            rid=seq.rid,
            text=text,
            token_ids=list(seq.output_ids),
            prompt_tokens=len(seq.prompt_ids),
            completion_tokens=len(seq.output_ids),
            finish_reason=seq.finish_reason or "stop",
            timing=timing,
            snapshot=self._prefill_snapshots.pop(seq.rid, None),
        )

    # --- snapshot plane ---------------------------------------------------
    def _model_sig(self) -> Dict[str, Any]:
        """The shape contract a snapshot's KV pages must match. Weights are
        deliberately NOT part of the signature — the handoff plane assumes
        peers serve the same checkpoint (same queue, same model), which is
        also what the prefix cache and greedy bit-exactness already rely
        on."""
        return {
            "num_layers": int(self.model_config.num_layers),
            "num_kv_heads": int(self.model_config.num_kv_heads),
            "head_dim": int(self.model_config.head_dim_),
            "kv_dtype": str(jnp.dtype(self.cfg.kv_dtype)),
        }

    def _snapshot_seq(self, seq: Sequence) -> RequestSnapshot:
        """Host-serializable state of one unfinished sequence. KV pages
        come from the sequence's pending host restore (swap-preempted),
        or a device gather (prefilled and running), or not at all
        (waiting/mid-prefill — re-insertion re-prefills, which is the
        same recovery recompute preemption already performs)."""
        p = seq.params
        kv_k = kv_v = None
        kv_valid = 0
        if seq.restore is not None:
            r = seq.restore
            kv_k, kv_v, kv_valid = r.k, r.v, r.valid
        elif seq.prefilled and seq.rid in self.scheduler.running and seq.pages:
            kv_valid = seq.num_tokens - 1
            n = snapshot_mod.pages_for(kv_valid, self.cfg.page_size)
            if 0 < n <= len(seq.pages):
                with self._wd("snapshot_gather"):
                    kv_k, kv_v = self._kv_gather_np(seq.pages[:n])
            else:
                kv_valid = 0
        return RequestSnapshot(
            rid=seq.rid,
            model_sig=self._model_sig(),
            page_size=self.cfg.page_size,
            prompt_ids=list(seq.prompt_ids),
            output_ids=list(seq.output_ids),
            params=dataclasses.replace(p),
            # µs-scale PRNG-key fetch; the snapshot's KV gathers above
            # are the heavy reads and run bracketed.
            key_data=np.asarray(  # llmq: ignore[unguarded-device-fetch]
                make_base_key(p.seed, request_tag(seq.rid)), np.uint32
            ),
            epoch=seq.epoch,
            preempt_count=seq.preempt_count,
            detok_len=seq.detok_len,
            detok_text=seq.detok_text,
            kv_valid=kv_valid,
            kv_k=kv_k,
            kv_v=kv_v,
        )

    def _remove_extracted(self, seq: Sequence) -> None:
        if seq.rid in self.scheduler.running:
            was_prefilled = seq.prefilled
            # Pipeline is drained (extract paths drain first), so pages
            # release immediately — no watermark needed.
            self.scheduler.finish(seq, "extracted")
            if was_prefilled:
                self._dirty = True
        else:
            try:
                self.scheduler.waiting.remove(seq)
            except ValueError:
                pass
        seq.restore = None

    def extract_request(
        self,
        rid: str,
        *,
        finished: Optional[List[RequestOutput]] = None,
    ) -> RequestSnapshot:
        """Pull one in-flight request out of the engine as a
        :class:`RequestSnapshot` and remove it. Drains the run-ahead
        pipeline first so scheduler truth is current; outputs observed
        during that drain are appended to ``finished`` (pass a list to
        keep them — a request that finishes during the drain raises
        KeyError here but surfaces there). Greedy continuation after
        :meth:`insert_request` is bit-identical to never extracting."""
        out = finished if finished is not None else []
        self._drain(out)
        seq = self.scheduler.running.get(rid)
        if seq is None:
            seq = next(
                (s for s in self.scheduler.waiting if s.rid == rid), None
            )
        if seq is None or seq.finish_reason is not None:
            raise KeyError(f"no in-flight request {rid!r} to extract")
        snap = self._snapshot_seq(seq)
        self._remove_extracted(seq)
        self.snapshots_extracted += 1
        return snap

    def extract_all(
        self, *, finished: Optional[List[RequestOutput]] = None
    ) -> List[RequestSnapshot]:
        """Extract every unfinished request (drain-with-handoff). See
        :meth:`extract_request`."""
        out = finished if finished is not None else []
        self._drain(out)
        snaps: List[RequestSnapshot] = []
        for seq in list(self.scheduler.running.values()) + list(
            self.scheduler.waiting
        ):
            if seq.finish_reason is not None:
                continue
            snaps.append(self._snapshot_seq(seq))
            self._remove_extracted(seq)
            self.snapshots_extracted += 1
        return snaps

    # --- fault recovery ---------------------------------------------------
    def discard_pending(self, *, reuse_pool: bool = False) -> None:
        """Drop every in-flight dispatch result without fetching it.
        Fault recovery only: after a device fault the pending outputs
        are unreadable (wedged or poisoned), while the sequences' host
        state — ``output_ids`` up to the last *processed* step — is
        still consistent. Re-inserting their snapshots recomputes the
        lost iterations deterministically (same key chain, same step
        counts), so greedy output is token-identical to a fault-free
        run; only a little progress is repaid.

        ``reuse_pool`` is the in-place (same backend) restore flavor:
        deferred pages go back to the allocator instead of being
        abandoned — safe because any in-flight writes to them are
        device-stream-ordered before whatever reuses them next."""
        self._pending.clear()
        self._pending_decodes = 0
        # Dropped swap captures fall back to re-prefill on re-admission:
        # always correct, just repays the preempted prefix.
        self._pending_swaps.clear()
        self._processed_idx = self._dispatch_idx
        if reuse_pool:
            for _, pages, cacheable in self._deferred_pages:
                self.scheduler.release_pages(pages, cacheable)
        # Otherwise deferred pages would now be past their watermark, but
        # the pool they'd return to is being abandoned with the faulted
        # backend; just drop the bookkeeping.
        self._deferred_pages.clear()
        self._dirty = True

    def extract_for_rebuild(
        self, *, reuse_pool: bool = False
    ) -> Tuple[List[Tuple[RequestSnapshot, Optional[float]]], List[str]]:
        """Best-effort snapshot of every in-flight request after a
        device fault, for re-insertion into a rebuilt engine. In-flight
        dispatch results are discarded first (see
        :meth:`discard_pending`), then each sequence snapshots
        *independently* — per-request isolation, unlike
        :meth:`extract_all`, because a gather from a wedged or poisoned
        backend can itself fault. Returns ``(snapshots_with_deadlines,
        lost_rids)``: rows whose snapshot failed (wedged in the faulted
        dispatch) go in the second list and recover via the worker's
        requeue path instead."""
        self.discard_pending(reuse_pool=reuse_pool)
        snaps: List[Tuple[RequestSnapshot, Optional[float]]] = []
        lost: List[str] = []
        for seq in list(self.scheduler.running.values()) + list(
            self.scheduler.waiting
        ):
            if seq.finish_reason is not None:
                continue
            try:
                snap = self._snapshot_seq(seq)
            except Exception:  # noqa: BLE001 — per-row isolation
                logger.exception(
                    "fault recovery: snapshot of %s failed; the request "
                    "will requeue instead",
                    seq.rid,
                )
                lost.append(seq.rid)
                # Still remove it: in the same-backend (reuse_pool)
                # restore a row left behind would keep generating against
                # a future already resolved as a requeue — a duplicate.
                self._remove_extracted(seq)
                continue
            snaps.append((snap, seq.deadline_at))
            self._remove_extracted(seq)
            self.snapshots_extracted += 1
        return snaps, lost

    def degrade_for_oom(self) -> Optional[str]:
        """One rung of the HBM-OOM degradation ladder per call, in
        order: (1) demote refcount-0 prefix device pages to the host
        cold tier, (2) halve the run-ahead pipeline depth (fewer
        in-flight result buffers resident in HBM), (3) preempt one
        victim with swap-to-host. Returns the rung taken, or None when
        the ladder is dry — the caller then falls through to fault
        recovery (rebuild / dead-letter). Rungs never reset: a pool
        that OOMed stays degraded for the life of this engine."""
        self.hbm_oom_events += 1
        while self._oom_rung < 3:
            rung = self._oom_rung
            self._oom_rung += 1
            if rung == 0:
                if self.prefix_store is not None:
                    dropped = self.flush_prefix_to_host()
                    if dropped > 0:
                        self._oom_ladder_log.append("demote_prefix")
                        logger.warning(
                            "hbm_oom ladder: demoted %d prefix pages to "
                            "the host tier",
                            dropped,
                        )
                        return "demote_prefix"
            elif rung == 1:
                if self.cfg.runahead > 1:
                    self.cfg.runahead = max(1, self.cfg.runahead // 2)
                    self._oom_ladder_log.append("shrink_runahead")
                    logger.warning(
                        "hbm_oom ladder: run-ahead shrunk to %d",
                        self.cfg.runahead,
                    )
                    return "shrink_runahead"
            else:
                victim = next(
                    (
                        s
                        for s in reversed(
                            list(self.scheduler.running.values())
                        )
                        if s.prefilled and s.finish_reason is None
                    ),
                    None,
                )
                if victim is not None:
                    # Force the swap flavor for this one preemption: the
                    # point of the rung is freeing HBM *without* paying a
                    # re-prefill on top of an already-starved device.
                    prev = self.preempt_mode
                    self.preempt_mode = "swap"
                    try:
                        self._self_preempt_deferred(victim)
                    finally:
                        self.preempt_mode = prev
                    self._oom_ladder_log.append("preempt_swap")
                    logger.warning(
                        "hbm_oom ladder: swap-preempted %s", victim.rid
                    )
                    return "preempt_swap"
        return None

    def insert_request(
        self,
        snap: RequestSnapshot,
        *,
        deadline_at: Optional[float] = None,
    ) -> Sequence:
        """Re-insert an extracted request, here or on a different engine.
        KV pages are remapped to whatever physical pages admission hands
        out (repacked host-side if the page size differs); the sampling
        key chain is re-derived from (seed, rid) and verified against the
        snapshot bit-for-bit. A snapshot without KV re-prefills
        prompt+output instead — same math, same tokens."""
        sig, mine = dict(snap.model_sig), self._model_sig()
        if sig != mine:
            raise SnapshotCompatError(
                f"snapshot model signature {sig} does not match engine "
                f"{mine}"
            )
        if snap.rid in self.scheduler.running or any(
            s.rid == snap.rid for s in self.scheduler.waiting
        ):
            raise ValueError(
                f"request {snap.rid!r} is already in flight on this engine"
            )
        params = dataclasses.replace(snap.params)
        # µs-scale PRNG-key fetch at insert time, not a dispatch wait.
        expect = np.asarray(  # llmq: ignore[unguarded-device-fetch]
            make_base_key(params.seed, request_tag(snap.rid)), np.uint32
        )
        # Snapshot payload is already host bytes.
        got = np.asarray(snap.key_data, np.uint32)  # llmq: ignore[unguarded-device-fetch]
        if got.shape != expect.shape or not np.array_equal(got, expect):
            raise SnapshotCompatError(
                "sampling-key chain mismatch: the snapshot's base key does "
                "not re-derive from (seed, rid) on this engine"
            )
        need = len(
            set(params.stop_token_ids)
            | (set() if params.ignore_eos else self._eos_ids)
        )
        if need > self._stop_capacity:
            self._grow_stop_capacity(need)
        seq = Sequence(
            rid=snap.rid,
            prompt_ids=[int(t) for t in snap.prompt_ids],
            params=params,
            output_ids=[int(t) for t in snap.output_ids],
            # Fresh epoch lineage on this engine; +1 mirrors what a
            # preemption would have done to any stale in-flight rows.
            epoch=snap.epoch + 1,
            preempt_count=snap.preempt_count,
            detok_len=snap.detok_len,
            detok_text=snap.detok_text,
            deadline_at=deadline_at,
        )
        if deadline_at is not None:
            self._deadlines_enabled = True
        if (
            snap.kv_k is not None
            and snap.kv_v is not None
            and snap.kv_valid > 0
        ):
            if snap.kv_valid != seq.num_tokens - 1:
                raise SnapshotCompatError(
                    f"snapshot KV covers {snap.kv_valid} positions but "
                    f"{seq.num_tokens - 1} are needed to continue decode"
                )
            k, v = snap.kv_k, snap.kv_v
            if snap.page_size != self.cfg.page_size:
                n_dst = snapshot_mod.pages_for(
                    snap.kv_valid, self.cfg.page_size
                )
                k = snapshot_mod.repack_pages(
                    k, snap.kv_valid, self.cfg.page_size, n_dst
                )
                v = snapshot_mod.repack_pages(
                    v, snap.kv_valid, self.cfg.page_size, n_dst
                )
            seq.restore = KVRestore(k=k, v=v, valid=snap.kv_valid)
        self.total_prompt_tokens += len(seq.prompt_ids)
        self.scheduler.add_restored(seq)
        self.snapshots_inserted += 1
        return seq

    def _restore_batch(self, seqs: List[Sequence]) -> None:
        """Scatter admitted sequences' host KV pages back into the pools
        and mark them prefilled. The decode-state rows join via the dirty
        resync on the next dispatch — resync rebuilds all 13 leaves from
        scheduler truth, which now includes these rows."""
        for seq in seqs:
            r = seq.restore
            seq.restore = None
            n = r.k.shape[1]
            # admit() allocated pages for num_tokens+1 positions, which
            # always covers the ceil(valid/page) pages of data.
            assert n <= len(seq.pages), (n, len(seq.pages))
            # Host page-index list → numpy; no device value involved.
            self._kv_insert_np(seq.pages[:n], r.k, r.v)
            seq.prefilled = True
            if seq.t_prefill_start == 0.0:
                seq.t_prefill_start = time.monotonic()
            self.scheduler.register_prefix(seq)
            self.kv_restores += 1
        self._dirty = True

    # --- numerics-integrity plane ----------------------------------------
    def _canary_generate(self) -> List[int]:
        """Run the deterministic golden prompt to completion on an idle
        core and return the greedy token ids. The prompt is fixed small
        ids (valid in any vocab), temperature 0, EOS ignored — the only
        sources of variance left are the weights and the compute, which
        is exactly what the canary is meant to witness."""
        v = self.model_config.vocab_size
        prompt = [(i * 7 + 1) % v for i in range(8)]
        self.add_request(
            "__canary__",
            prompt_ids=prompt,
            params=SamplingParams(
                temperature=0.0, max_tokens=8, ignore_eos=True
            ),
        )
        tokens: List[int] = []
        for _ in range(256):  # bounded: 8 tokens needs far fewer steps
            for out in self.step():
                if out.rid == "__canary__":
                    tokens = list(out.token_ids)
            if not self.has_work:
                break
        return tokens

    def _generate_canary(self) -> List[int]:
        """Record the golden canary tokens at engine build (idle core,
        fresh weights — by construction the trusted reference)."""
        from llmq_tpu.engine import integrity as integrity_mod

        golden = self._canary_generate()
        logger.info(
            "canary golden recorded: %d token(s), fold=%s",
            len(golden),
            integrity_mod.token_fold(golden),
        )
        return golden

    def run_canary(self) -> bool:
        """Replay the golden prompt and compare greedy tokens bit-exactly
        against the build-time recording. Only meaningful on an idle core
        (skipped otherwise — a busy core replays on the next idle sweep).
        A mismatch (or a guard trip during the replay) counts as a canary
        failure; the caller decides escalation."""
        if self._canary_golden is None:
            return True
        if self.has_work:
            return True
        self.canary_runs += 1
        try:
            got = self._canary_generate()
        except Exception as exc:  # noqa: BLE001 — a trip IS a failure
            self.canary_failures += 1
            # The failed replay may have left the canary sequence and its
            # pipeline entries behind; clear them so the core is reusable.
            self.abort_all("canary_failed")
            logger.error("canary replay raised: %s", exc)
            raise
        if got == self._canary_golden:
            return True
        from llmq_tpu.engine import integrity as integrity_mod

        self.canary_failures += 1
        logger.error(
            "canary FAILURE: got %s (fold=%s) want %s (fold=%s)",
            got,
            integrity_mod.token_fold(got),
            self._canary_golden,
            integrity_mod.token_fold(self._canary_golden),
        )
        return False

    def audit_weights(self) -> List[str]:
        """Re-digest every parameter leaf on device and diff against the
        build-time baseline. A non-empty return names the leaves whose
        HBM bytes changed since load — weight corruption, as opposed to
        the transient compute errors the logit guard catches. Two reads
        of intact HBM always agree, so false positives are impossible;
        the digest is associative, so sharded leaves fold identically."""
        if self._weight_baseline is None:
            return []
        from llmq_tpu.engine import integrity as integrity_mod

        self.weight_audits += 1
        with self._wd("weight_audit"):
            current = integrity_mod.digest_params(self.params)
        mismatched = integrity_mod.diff_digests(
            self._weight_baseline, current
        )
        if mismatched:
            self.weight_audit_mismatches += len(mismatched)
            self._last_audit_mismatch = list(mismatched)
            logger.error(
                "weight audit: %d leaf/leaves changed in HBM since load: %s",
                len(mismatched),
                mismatched[:8],
            )
        return mismatched

    def kv_spot_check(self, max_pages: int = 4) -> List[str]:
        """Read-stability spot check of the paged KV cache: gather a
        deterministic sample of in-use pages twice and compare blake2b
        digests. Unlike the weight audit there is no load-time baseline
        (KV churns constantly), so the check detects pages that do not
        read back consistently — the HBM-corruption signature that
        poisons every sequence sharing the page."""
        in_use = sorted(
            {
                p
                for s in self.scheduler.running.values()
                for p in s.pages
            }
        )
        if not in_use:
            return []
        from llmq_tpu.engine import integrity as integrity_mod

        stride = max(1, len(in_use) // max_pages)
        sample = in_use[::stride][:max_pages]
        # Host page-index list → numpy; no device value involved.
        idx = np.asarray(sample, np.int32)  # llmq: ignore[unguarded-device-fetch]
        self.kv_spot_checks += 1
        mismatched: List[str] = []
        with self._wd("kv_spot"):
            # Two independent full gathers (per-stage under pp: the
            # helper concatenates stage slabs back to the full layer
            # stack, so one digest still covers every stage's HBM).
            k1, v1 = self._kv_gather_np(idx)
            k2, v2 = self._kv_gather_np(idx)
        for name, first, second in (("k", k1, k2), ("v", v1, v2)):
            # gather returns [L, n, page, kv, d]; digest per sampled page.
            da = integrity_mod.page_digests(np.moveaxis(first, 1, 0))
            db = integrity_mod.page_digests(np.moveaxis(second, 1, 0))
            mismatched.extend(
                f"{name}:page{p}"
                for p, x, y in zip(sample, da, db)
                if x != y
            )
        if mismatched:
            logger.error(
                "kv spot check: %d page read(s) unstable: %s",
                len(mismatched),
                mismatched,
            )
        return mismatched

    def maybe_idle_integrity(self) -> Optional[str]:
        """Idle-step background sweep (engine thread, between batches):
        run whichever integrity checks have hit their cadence. Returns a
        failure detail string when something is wrong — the caller (the
        async loop) raises it into the device-fault containment path —
        or None when clean / nothing due."""
        now = time.monotonic()
        if (
            self._weight_baseline is not None
            and self.weight_audit_every > 0
            and now >= self._next_weight_audit
        ):
            self._next_weight_audit = now + self.weight_audit_every
            bad = self.audit_weights()
            bad.extend(self.kv_spot_check())
            if bad:
                return f"weight/KV audit mismatch: {bad[:8]}"
        if (
            self._canary_golden is not None
            and self.canary_every > 0
            and now >= self._next_canary
            and not self.has_work
        ):
            self._next_canary = now + self.canary_every
            if not self.run_canary():
                return "canary replay diverged from golden tokens"
        return None

    def integrity_status(self) -> str:
        """One-word integrity verdict for heartbeats: ``ok`` until any
        audit/canary evidence of corruption, then ``suspect``."""
        if (
            self.weight_audit_mismatches
            or self.canary_failures
            or self._last_audit_mismatch
        ):
            return "suspect"
        return "ok"

    def abort_all(self, note: str = "aborted") -> None:
        """Drop every running/waiting sequence and release their pages —
        recovery hook after a failed step, so the loop doesn't re-step a
        half-updated batch forever."""
        if self._pending:
            try:  # wait out in-flight steps; discard their results
                # Deliberately unbracketed: abort_all runs on the failure
                # path where the watchdog may have already tripped — a
                # second trip here would shadow the original fault.
                np.asarray(self._pending[-1][2])  # llmq: ignore[unguarded-device-fetch]
            except Exception:  # noqa: BLE001 — the step itself failed
                pass
            self._processed_idx = self._pending[-1][0]
            self._pending.clear()
            self._pending_decodes = 0
        # Swap captures reference the pool being torn down; their
        # sequences are gone with the abort anyway.
        self._pending_swaps.clear()
        self._flush_deferred()
        # The prefix cache must not survive an abort: the KV buffers may
        # be rebuilt (zeroed) below, and a cached hash pointing at a page
        # of the new pool would hand future requests empty context. The
        # host tier goes with it — its blobs were gathered from the same
        # now-untrusted buffers (invalidate_prefix_cache suppresses
        # demotion, so nothing re-parks during the teardown either).
        self.scheduler.invalidate_prefix_cache()
        if self.prefix_store is not None:
            self.prefix_store.invalidate()
        for seq in list(self.scheduler.running.values()):
            self.scheduler.finish(seq, note)
        self.scheduler.waiting.clear()
        self._dirty = True
        # A failed step may have consumed its donated inputs (kv/state
        # buffers deleted). KV contents are irrelevant now — every
        # sequence is gone — but the buffers must exist for the next
        # prefill, so rebuild any that died with the failed executable.
        if self.pp > 1:
            for s, (lo, hi) in enumerate(self._stage_ranges):
                try:
                    dead = (
                        self.k_pages[s].is_deleted()
                        or self.v_pages[s].is_deleted()
                    )
                except Exception:  # noqa: BLE001
                    dead = True
                if dead:
                    k_s, v_s = make_kv_pages(
                        self.model_config,
                        self.scheduler.config.num_pages,
                        self.cfg.page_size,
                        dtype=self.cfg.kv_dtype,
                        num_layers=hi - lo,
                    )
                    self.k_pages[s] = jax.device_put(
                        k_s, self._kv_formats[s]
                    )
                    self.v_pages[s] = jax.device_put(
                        v_s, self._kv_formats[s]
                    )
            return
        try:
            dead = self.k_pages.is_deleted() or self.v_pages.is_deleted()
        except Exception:  # noqa: BLE001
            dead = True
        if dead:
            k_pages, v_pages = make_kv_pages(
                self.model_config,
                self.scheduler.config.num_pages,
                self.cfg.page_size,
                dtype=self.cfg.kv_dtype,
            )
            self.k_pages = jax.device_put(k_pages, self._kv_format)
            self.v_pages = jax.device_put(v_pages, self._kv_format)

    # --- metrics ----------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        elapsed = max(1e-9, time.monotonic() - self._started_at)
        s = self.scheduler.stats()
        from llmq_tpu.ops import dispatch as _dispatch

        kern, _fused = _dispatch.decode_kernel_plan(
            self.model_config.num_heads,
            self.model_config.num_kv_heads,
            mesh=self.mesh,
        )
        s.update(
            prompt_tokens=self.total_prompt_tokens,
            generated_tokens=self.total_generated_tokens,
            decode_steps=self.decode_steps,
            # Host round trips: with fused decode blocks the host
            # dispatches/snapshots/fetches once per decode_block device
            # iterations, so dispatches <= ceil(decode_steps / K).
            decode_dispatches=self.decode_dispatches,
            decode_block=self.cfg.decode_block,
            # Speculation health: accepted/proposed drafts. A dispatch
            # emits 1 + (accepted this step) tokens, so tok/s scales
            # with acceptance_rate at fixed step time (PERF_NOTES math).
            spec_tokens=self.cfg.spec_tokens,
            spec_proposed=self.spec_proposed,
            spec_accepted=self.spec_accepted,
            acceptance_rate=(
                self.spec_accepted / self.spec_proposed
                if self.spec_proposed
                else 0.0
            ),
            prefills=self.prefills,
            # Piggyback scheduling: fused decode+prefill dispatches and
            # the prompt positions they carried — nonzero proves the
            # mixed path actually ran (ISSUE 6 acceptance line).
            mixed_step=self.mixed_step,
            mixed_steps=self.mixed_steps,
            mixed_prefill_tokens=self.mixed_prefill_tokens,
            # Snapshot plane: swap-to-host preemption and extract/insert
            # traffic. kv_restores counts admissions that scattered host
            # KV back instead of re-prefilling.
            preempt_mode=self.preempt_mode,
            swap_preempts=self.swap_preempts,
            kv_restores=self.kv_restores,
            snapshots_extracted=self.snapshots_extracted,
            snapshots_inserted=self.snapshots_inserted,
            # Prefix reuse plane: prompt positions actually computed vs
            # reused, host-tier traffic, and shipping counters. A
            # templated batch with working reuse shows prefill_tokens
            # well below prompt_tokens.
            prefill_tokens=self.prefill_tokens,
            prefix_demotes=self.prefix_demotes,
            prefix_promotes=self.prefix_promotes,
            prefix_chunks_exported=self.prefix_chunks_exported,
            prefix_chunks_ingested=self.prefix_chunks_ingested,
            tokens_per_sec=self.total_generated_tokens / elapsed,
            devices=int(np.prod(list(self.full_mesh.shape.values()))),
            # What this engine actually runs — the autotuned kernel and
            # the pool dtype — so operators can see the calibration in
            # heartbeats instead of guessing from env vars.
            decode_kernel=kern,
            kv_dtype=str(jnp.dtype(self.cfg.kv_dtype)),
            # Resolved at build time (env pin / config / autotune) — may
            # differ from cfg.tp_overlap ("auto", or forced off on tp=1).
            tp_overlap=self.tp_overlap,
            # Latency percentiles (ms; None until the histogram has data)
            # and per-kind recent dispatch wall-times from the 256-entry
            # ring buffers.
            ttft_p50_ms=to_ms(self.ttft_hist.percentile(0.50)),
            ttft_p95_ms=to_ms(self.ttft_hist.percentile(0.95)),
            ttft_p99_ms=to_ms(self.ttft_hist.percentile(0.99)),
            itl_p50_ms=to_ms(self.itl_hist.percentile(0.50)),
            itl_p95_ms=to_ms(self.itl_hist.percentile(0.95)),
            itl_p99_ms=to_ms(self.itl_hist.percentile(0.99)),
            dispatch_ms={
                kind: {
                    "recent_avg": round(sum(ring) / len(ring) * 1000.0, 3),
                    "count": self._dispatch_hists[kind].total,
                }
                for kind, ring in self._dispatch_rings.items()
                if ring
            },
        )
        if self.cfg.spec_tokens > 0:
            # What speculation actually dispatches: the multi-query
            # verify resolves through its own plan, not the decode ladder.
            s["verify_kernel"] = _dispatch.verify_kernel_plan(
                self.model_config.num_heads,
                self.model_config.num_kv_heads,
                mesh=self.mesh,
            )[0]
        if self.mixed_step == "on":
            s["mixed_kernel"] = _dispatch.mixed_kernel_plan(
                self.model_config.num_heads,
                self.model_config.num_kv_heads,
                mesh=self.mesh,
            )[0]
        if self.prefix_store is not None:
            s.update(self.prefix_store.stats())
        # Pipeline parallelism (superset-only: pp=1 engines publish
        # byte-identical heartbeats). The bubble fraction is the GPipe
        # analytic (pp-1)/(m+pp-1) with the decode run-ahead depth (K
        # iterations per dispatch × runahead dispatches in flight) as
        # the microbatch count — the number the bench pp rung reports.
        if self.pp > 1:
            m = max(1, self.cfg.decode_block * self.cfg.runahead)
            s["pp_stages"] = self.pp
            s["pp_boundary_bytes"] = self.pp_boundary_bytes
            s["pp_boundary_transfers"] = self.pp_boundary_transfers
            s["pp_bubble_fraction"] = round(
                pp_mod.bubble_fraction(m, self.pp), 6
            )
            s["pp_boundary_bytes_per_token"] = (
                pp_mod.boundary_bytes_per_token(
                    self.model_config.hidden_size
                )
            )
            s["pp_wire"] = "codec" if self.pp_wire else "device"
        # SLO priority plane (superset-only: appears once the first
        # interactive request arrived — priority-free engines publish
        # byte-identical stats).
        if self._priority_enabled:
            s["priority_preemptions"] = self.priority_preemptions
            s["interactive_decode_block"] = self.interactive_decode_block
            s["ttft_p50_ms_interactive"] = to_ms(
                self.ttft_hist_interactive.percentile(0.50)
            )
            s["ttft_p95_ms_interactive"] = to_ms(
                self.ttft_hist_interactive.percentile(0.95)
            )
            s["itl_p50_ms_interactive"] = to_ms(
                self.itl_hist_interactive.percentile(0.50)
            )
            s["itl_p95_ms_interactive"] = to_ms(
                self.itl_hist_interactive.percentile(0.95)
            )
            s["tokens_interactive"] = self.class_tokens["interactive"]
            s["tokens_batch"] = self.class_tokens["batch"]
            s["finished_interactive"] = self.class_finished["interactive"]
            s["finished_batch"] = self.class_finished["batch"]
        # Client-disconnect cancellation (superset-only: appears once a
        # cancel actually landed).
        if self.cancellations:
            s["cancellations"] = self.cancellations
        # Disaggregated serving (superset-only: appears once this engine
        # has finished a prefill-only request at the phase boundary).
        if self.prefill_done:
            s["prefill_done"] = self.prefill_done
        # Fleet self-healing counters (superset-only: appear once moved).
        if self.deadline_expirations:
            s["deadline_expirations"] = self.deadline_expirations
        if self.swap_refused:
            s["swap_refused"] = self.swap_refused
        # Device-fault containment (superset-only: the watchdog block
        # appears only when the watchdog is on, the OOM block only after
        # an allocation fault — defaults publish neither).
        if self.watchdog is not None:
            s["watchdog_trips"] = self.watchdog.trips
            s["last_dispatch_ok_age_s"] = round(
                self.watchdog.last_ok_age_s(), 3
            )
            wedged = self.watchdog.wedged_kind()
            if wedged is not None:
                # A dispatch is in flight AND past its deadline right now
                # — the signature that separates a wedged engine from a
                # healthy idle one (whose ok-age also grows, jobless).
                s["wedged_dispatch"] = wedged
        if self.hbm_oom_events:
            s["hbm_oom_events"] = self.hbm_oom_events
            s["oom_degradations"] = list(self._oom_ladder_log)
        # Numerics-integrity plane (superset-only: each block appears
        # once its knob is on / its counter moved — default-off
        # heartbeats stay byte-identical to pre-integrity builds).
        if self.guard_trips:
            s["guard_trips"] = self.guard_trips
        if self.weight_audits:
            s["weight_audits"] = self.weight_audits
            s["weight_audit_mismatches"] = self.weight_audit_mismatches
            s["kv_spot_checks"] = self.kv_spot_checks
            if self._last_audit_mismatch:
                s["last_audit_mismatch"] = list(self._last_audit_mismatch)
        if self.canary_runs:
            s["canary_runs"] = self.canary_runs
            s["canary_failures"] = self.canary_failures
        if (
            self.logit_guard == "on"
            or self.weight_audit_every > 0
            or self.canary_every > 0
        ):
            s["integrity"] = self.integrity_status()
        gov = get_governor()
        if gov.enabled:
            s["host_mem"] = gov.stats()
        return s


@dataclasses.dataclass
class HandoffOutput:
    """What :meth:`AsyncEngine.handoff` resolves an in-flight request
    with instead of a :class:`RequestOutput`: the request's snapshot (or
    None when it never entered the engine — no partial state to carry)
    and the count of tokens already generated (the resume offset for
    result-side dedup)."""

    rid: str
    snapshot: Optional[RequestSnapshot]
    emitted: int = 0


#: Hard ceiling on one in-process fault recovery (extract + rebuild +
#: re-insert). A device wedged badly enough that the *recovery* blocks
#: past this is unrecoverable in-process: the backstop hard-exits so the
#: orphan janitor reclaims the worker's affinity queue and its jobs
#: requeue, instead of a zombie holding them forever.
REBUILD_HARD_EXIT_S = 180.0


def _hard_exit_wedged(reason: str) -> None:
    logger.critical(
        "engine rebuild after %s exceeded %.0fs — process is wedged "
        "beyond in-process recovery; hard-exiting for janitor reclaim",
        reason,
        REBUILD_HARD_EXIT_S,
    )
    os._exit(86)


class AsyncEngine:
    """Async facade: step loop on a dedicated thread, asyncio-awaitable
    results (the surface the reference consumed from AsyncLLMEngine)."""

    def __init__(self, core: EngineCore) -> None:
        self.core = core
        self._intake: "queue.Queue[Optional[tuple]]" = queue.Queue()
        self._futures: Dict[str, Future] = {}
        self._wake = threading.Event()
        self._stop = False
        self._draining = False
        self._handoff_requested = False
        self._handoff_event: Optional[threading.Event] = None
        self._handoff_results: List[HandoffOutput] = []
        # Device-fault containment (all optional; workers wire them):
        # rebuild_core() returns a fresh EngineCore in a fresh backend —
        # when set, a classified device fault rebuilds in-process and
        # re-inserts every restorable request instead of failing the
        # batch. on_device_fault(reason) is the worker's breaker
        # notification (called on the engine thread; must be cheap /
        # thread-safe).
        self.rebuild_core: Optional[Any] = None
        self.on_device_fault: Optional[Any] = None
        self.engine_rebuilds = 0
        self.last_fault_reason: Optional[str] = None
        # Trips recorded by watchdogs of cores already rebuilt away;
        # stats() adds them so the counter never moves backwards.
        self._prior_watchdog_trips = 0
        # Blame attribution for numerical faults: rid -> trip count.
        # First trip re-runs the request on a rebuilt core (device
        # blamed); a second trip classifies the job as poison. Entries
        # pop on clean completion or on the poison verdict, so the map
        # never outlives its requests.
        self._numerical_probation: Dict[str, int] = {}
        # rid -> [(event_name, t_mono, fields)] recorded during fault
        # recovery; workers pop these into the request trace.
        self._fault_events: Dict[str, List[Tuple[str, float, Dict[str, Any]]]] = {}
        self._fault_lock = threading.Lock()
        # Closures marshalled onto the engine thread (prefix-tier export/
        # ingest touch the device pools, which the step loop donates).
        self._calls: "queue.Queue[Tuple[Any, Future]]" = queue.Queue()
        # rid -> per-token callback (streaming deltas). Fired on the
        # ENGINE thread for every surviving token, so callbacks must be
        # cheap and thread-safe (workers bridge with
        # loop.call_soon_threadsafe). Keyed per-request: jobs that never
        # register one cost a single dict miss per token.
        self._token_cbs: Dict[str, Any] = {}
        core.on_token = self._dispatch_token
        self._thread = threading.Thread(
            target=self._run, name="llmq-engine", daemon=True
        )
        self._thread.start()

    # --- public surface ---------------------------------------------------
    async def generate(
        self,
        *,
        rid: str,
        prompt: Optional[str] = None,
        messages: Optional[List[Dict[str, str]]] = None,
        prompt_ids: Optional[List[int]] = None,
        params: Optional[SamplingParams] = None,
        deadline_at: Optional[float] = None,
        prefill_only: bool = False,
        priority: str = "batch",
    ) -> RequestOutput:
        import asyncio

        if self._draining:
            raise RuntimeError("engine is draining for handoff")
        fut: Future = Future()
        self._futures[rid] = fut
        self._intake.put(
            (rid, prompt, messages, prompt_ids, params, None, deadline_at,
             prefill_only, priority)
        )
        self._wake.set()
        try:
            return await asyncio.wrap_future(fut)
        finally:
            self._futures.pop(rid, None)

    async def resume(
        self,
        *,
        rid: str,
        snapshot: RequestSnapshot,
        deadline_at: Optional[float] = None,
    ) -> RequestOutput:
        """Continue a request from a :class:`RequestSnapshot` (published
        by a peer's drain-with-handoff). Completes exactly like generate();
        may itself resolve with a HandoffOutput if THIS engine drains."""
        import asyncio

        if self._draining:
            raise RuntimeError("engine is draining for handoff")
        fut: Future = Future()
        self._futures[rid] = fut
        self._intake.put(
            (rid, None, None, None, None, snapshot, deadline_at, False,
             "batch")
        )
        self._wake.set()
        try:
            return await asyncio.wrap_future(fut)
        finally:
            self._futures.pop(rid, None)

    def generate_sync(self, *, rid: str, **kwargs) -> RequestOutput:
        fut: Future = Future()
        self._futures[rid] = fut
        self._intake.put(
            (
                rid,
                kwargs.get("prompt"),
                kwargs.get("messages"),
                kwargs.get("prompt_ids"),
                kwargs.get("params"),
                kwargs.get("snapshot"),
                kwargs.get("deadline_at"),
                kwargs.get("prefill_only", False),
                kwargs.get("priority", "batch"),
            )
        )
        self._wake.set()
        try:
            return fut.result()
        finally:
            self._futures.pop(rid, None)

    def handoff(self, timeout: float = 120.0) -> List[HandoffOutput]:
        """Drain-with-handoff (thread-safe, called from any thread): let
        in-flight device steps land, extract every unfinished request as
        a snapshot, and resolve its pending future with a
        :class:`HandoffOutput` instead of a RequestOutput. New
        generate()/resume() calls fail fast afterwards. Returns the
        handoffs; requests that finish during the drain resolve with
        their normal RequestOutput and are not in the list."""
        self._draining = True  # refuse new intake even before the drain
        if not self._thread.is_alive():
            return []
        self._handoff_results = []
        self._handoff_event = threading.Event()
        self._handoff_requested = True
        self._wake.set()
        if not self._handoff_event.wait(timeout=timeout):
            logger.warning("engine handoff timed out after %.1fs", timeout)
        return self._handoff_results

    def stats(self) -> Dict[str, Any]:
        s = self.core.stats()
        if self._prior_watchdog_trips and "watchdog_trips" in s:
            # Trips survive rebuilds: the faulted core's watchdog died
            # with it, but the count is a worker-lifetime monotonic.
            s["watchdog_trips"] += self._prior_watchdog_trips
        return s

    @property
    def watchdog_trips(self) -> int:
        """Worker-lifetime watchdog trip count, across engine rebuilds."""
        wd = getattr(self.core, "watchdog", None)
        return self._prior_watchdog_trips + (wd.trips if wd else 0)

    # --- streaming / cancellation ----------------------------------------
    def _dispatch_token(self, seq: Any, token: int) -> None:
        """EngineCore.on_token bridge (engine thread): route a surviving
        token to the request's registered callback, if any. Callback
        errors are swallowed — a broken stream consumer must not take
        down the step loop or the other requests in the batch."""
        cb = self._token_cbs.get(seq.rid)
        if cb is None:
            return
        try:
            cb(token, len(seq.output_ids))
        except Exception:  # noqa: BLE001 — consumer bug, not engine fault
            logger.exception("token callback for %s failed", seq.rid)

    def set_token_callback(self, rid: str, cb: Any) -> None:
        """Register ``cb(token, n_out)`` for one request's streaming
        deltas. Fired on the engine thread for each token that survives
        the stop check; ``n_out`` is the output length *including* this
        token (its 1-based index), so consumers can place tokens by
        absolute position and stay idempotent across fault-recovery
        replays. Register before generate() to see every token."""
        self._token_cbs[rid] = cb

    def clear_token_callback(self, rid: str) -> None:
        self._token_cbs.pop(rid, None)

    def cancel(self, rid: str) -> None:
        """Request cancellation of one in-flight request (thread-safe,
        non-blocking). Marshalled onto the engine thread; the request
        finishes with finish_reason='cancelled' through the normal output
        path (pages freed, future resolved), or is silently dropped from
        the waiting queue. Unknown rids are remembered briefly by the
        core so a cancel racing the intake drain still lands."""
        if not self._thread.is_alive():
            return
        self._calls.put((lambda: self.core.cancel_request(rid), Future()))
        self._wake.set()

    def call_on_engine(self, fn, timeout: float = 30.0):
        """Run ``fn()`` on the engine thread and return its result.
        Device-pool access (gathers, inserts) races the step loop's
        buffer donation from any other thread — everything that touches
        ``core.k_pages``/``v_pages`` outside the loop goes through here."""
        if not self._thread.is_alive():
            return fn()  # thread gone: no donation race left to lose
        fut: Future = Future()
        self._calls.put((fn, fut))
        self._wake.set()
        return fut.result(timeout=timeout)

    def export_prefix_chunks(self, digests_hex: List[str]) -> List[str]:
        """Thread-safe :meth:`EngineCore.export_prefix_chunks`."""
        return self.call_on_engine(
            lambda: self.core.export_prefix_chunks(digests_hex)
        )

    def ingest_prefix_chunks(self, chunks_b64: List[str]) -> int:
        """Thread-safe :meth:`EngineCore.ingest_prefix_chunks`."""
        return self.call_on_engine(
            lambda: self.core.ingest_prefix_chunks(chunks_b64)
        )

    def hot_prefix_chains(self, n: int = 8) -> List[str]:
        """Heartbeat helper; reads host-side maps only, but runs on the
        engine thread anyway so the dicts aren't mutated mid-iteration."""
        try:
            return self.call_on_engine(
                lambda: self.core.hot_prefix_chains(n), timeout=5.0
            )
        except Exception:  # noqa: BLE001 — advertisement is best-effort
            return []

    def missing_prefix_digests(self, digests_hex: List[str]) -> List[str]:
        """Thread-safe want-list check; [] on any failure (the fetch
        path treats "nothing missing" as "nothing to fetch")."""
        try:
            return self.call_on_engine(
                lambda: self.core.missing_prefix_digests(digests_hex),
                timeout=5.0,
            )
        except Exception:  # noqa: BLE001 — fetch is best-effort
            return []

    def shutdown(self) -> None:
        self._stop = True
        self._wake.set()
        self._thread.join(timeout=30)

    # --- fault recovery ---------------------------------------------------
    def pop_fault_events(self, rid: str) -> List[Tuple[str, float, Dict[str, Any]]]:
        """Take (and clear) the fault-recovery events recorded for one
        request: ``(name, t_mono, fields)`` tuples the worker projects
        onto the request trace. Thread-safe; [] when none."""
        with self._fault_lock:
            return self._fault_events.pop(rid, [])

    def _record_fault_event(
        self, rids: List[str], name: str, **fields: Any
    ) -> None:
        t = time.monotonic()
        with self._fault_lock:
            for rid in rids:
                self._fault_events.setdefault(rid, []).append(
                    (name, t, dict(fields))
                )

    def _degrade_and_restore(self, exc: Exception) -> bool:
        """Absorb one HBM-OOM fault on the SAME backend: take one rung of
        the degradation ladder, then restore every in-flight request from
        its host-truth snapshot. The faulted step may already have
        advanced device state past the last *processed* result (the
        dispatched block's outputs are lost with the exception), so a
        blind re-step would silently skip those tokens — restoring from
        snapshots recomputes them deterministically instead (same key
        chain, same step counts: greedy output stays token-identical).
        Returns False when the ladder is dry or the restore itself
        faults; the caller then falls through to the rebuild hammer."""
        rung = None
        try:
            rung = self.core.degrade_for_oom()
        except Exception:  # noqa: BLE001 — ladder best-effort
            logger.exception("hbm_oom degradation failed")
        if rung is None:
            return False
        affected = [
            rid for rid, fut in list(self._futures.items()) if not fut.done()
        ]
        self._record_fault_event(affected, "device_fault", reason=FAULT_OOM)
        for rid in affected:
            emit_trace_event(rid, "device_fault", reason=FAULT_OOM)
        try:
            snaps, lost = self.core.extract_for_rebuild(reuse_pool=True)
        except Exception:  # noqa: BLE001 — fall through to rebuild
            logger.exception(
                "hbm_oom restore: extraction failed; falling through to "
                "engine rebuild"
            )
            return False
        lost_set = set(lost)
        restored = 0
        for snap, deadline_at in snaps:
            try:
                self.core.insert_request(snap, deadline_at=deadline_at)
                restored += 1
            except Exception:  # noqa: BLE001 — per-row isolation
                logger.exception(
                    "hbm_oom restore: re-insert of %s failed; requeueing",
                    snap.rid,
                )
                lost_set.add(snap.rid)
        for rid in lost_set:
            fut = self._futures.get(rid)
            if fut is not None and not fut.done():
                fut.set_result(HandoffOutput(rid=rid, snapshot=None, emitted=0))
        self._record_fault_event(
            affected,
            "oom_degraded",
            rung=rung,
            restored=restored,
            requeued=len(lost_set),
        )
        logger.warning(
            "hbm_oom (%s) absorbed by degradation ladder (%s): %d "
            "request(s) restored in place, %d requeued",
            exc,
            rung,
            restored,
            len(lost_set),
        )
        return True

    def _recover_numerical(self, exc: Exception) -> bool:
        """Blame-attributed recovery for a numerical fault (logit-guard
        trip, failed weight/KV audit, or canary divergence). First trip
        for a request presumes the DEVICE is at fault: rebuild the core
        in a fresh backend (weights re-streamed from the trusted source),
        re-insert the suspects from their snapshots, and let greedy
        determinism replay them token-identically. A request whose
        re-run trips AGAIN is poison — its input deterministically
        breaks the numerics — so its future fails with a classified
        DeviceFaultError (the worker ladder quarantines it with
        ``x-failure-reason=numerical_fault``) instead of hot-looping
        rebuilds forever. Returns False when no rebuild path is wired
        (fall through to the batch-abort path)."""
        if self.rebuild_core is None:
            return False
        suspects = tuple(getattr(exc, "suspects", ()) or ())
        poison = [r for r in suspects if r in self._numerical_probation]
        fresh = [r for r in suspects if r not in self._numerical_probation]
        for rid in fresh:
            self._numerical_probation[rid] = 1
        if poison:
            logger.error(
                "numerical fault re-tripped by %s — poison job(s); "
                "quarantining instead of rebuilding again",
                poison,
            )
        if not self._rebuild_after_fault(
            FAULT_NUMERICAL, exc, drop=frozenset(poison)
        ):
            return False
        failure = DeviceFaultError(
            FAULT_NUMERICAL,
            f"request re-tripped the numerics guard after a rebuild: {exc}",
        )
        for rid in poison:
            self._numerical_probation.pop(rid, None)
            self._record_fault_event([rid], "poison_numerical")
            emit_trace_event(rid, "poison_numerical")
            fut = self._futures.get(rid)
            if fut is not None and not fut.done():
                fut.set_exception(failure)
        # Device-blamed path: before the rebuilt core takes traffic, it
        # re-verifies its weights and replays the canary (both recorded
        # fresh by its own build) — a chip that is still corrupting
        # fails here instead of on user requests.
        try:
            if self.core._weight_baseline is not None:
                self.core.audit_weights()
            if self.core._canary_golden is not None:
                self.core.run_canary()
        except Exception:  # noqa: BLE001 — re-verify is best-effort
            logger.exception("post-rebuild integrity re-verify failed")
        return True

    def _rebuild_after_fault(
        self,
        reason: str,
        exc: Exception,
        drop: frozenset = frozenset(),
    ) -> bool:
        """On the engine thread: contain a classified device fault by
        rebuilding the EngineCore in a fresh backend in-process. Every
        restorable request re-inserts from its snapshot and resumes
        (greedy token-identical — same key chain, same step counts);
        rows wedged in the faulted dispatch resolve with a snapshot-less
        HandoffOutput so the worker requeues them whole. Returns False
        to fall through to the batch-abort path (rebuild unavailable or
        itself failed). A recovery that *hangs* — the device wedged so
        hard that even extraction or the rebuild blocks forever — trips
        the hard-exit backstop, and the orphan janitor reclaims this
        worker's queue. Requests named in ``drop`` are neither
        re-inserted nor requeued — the caller has already decided their
        fate (poison verdicts fail their futures directly)."""
        logger.error(
            "device fault (%s): %s — attempting in-process engine rebuild",
            reason,
            exc,
        )
        self.last_fault_reason = reason
        if self.on_device_fault is not None:
            try:
                self.on_device_fault(reason)
            except Exception:  # noqa: BLE001 — observer must not block us
                logger.exception("on_device_fault callback failed")
        affected = [
            rid for rid, fut in list(self._futures.items()) if not fut.done()
        ]
        self._record_fault_event(affected, "device_fault", reason=reason)
        for rid in affected:
            emit_trace_event(rid, "device_fault", reason=reason)
        timer = threading.Timer(
            REBUILD_HARD_EXIT_S, _hard_exit_wedged, args=(reason,)
        )
        timer.daemon = True
        timer.start()
        try:
            old = self.core
            try:
                snaps, lost = old.extract_for_rebuild()
            except Exception:  # noqa: BLE001 — extraction is best-effort
                logger.exception(
                    "fault recovery: extraction failed outright; every "
                    "in-flight request will requeue"
                )
                snaps, lost = [], list(affected)
            old.stop_watchdog()
            old_wd = getattr(old, "watchdog", None)
            if old_wd is not None:
                self._prior_watchdog_trips += old_wd.trips
            try:
                new_core = self.rebuild_core()
            except Exception:  # noqa: BLE001 — fall back to batch abort
                logger.exception(
                    "fault recovery: rebuild failed; aborting the batch"
                )
                return False
            self.core = new_core
            new_core.on_token = self._dispatch_token  # streams survive rebuild
            del old  # free the faulted backend's buffers before stepping
            self.engine_rebuilds += 1
            lost_set = set(lost) - drop
            restored = 0
            for snap, deadline_at in snaps:
                if snap.rid in drop:
                    continue
                try:
                    new_core.insert_request(snap, deadline_at=deadline_at)
                    restored += 1
                except Exception:  # noqa: BLE001 — per-row isolation
                    logger.exception(
                        "fault recovery: re-insert of %s failed; requeueing",
                        snap.rid,
                    )
                    lost_set.add(snap.rid)
            # Wedged / unsnapshottable rows recover via the worker's
            # existing republish path: a snapshot-less HandoffOutput is
            # a plain requeue.
            for rid in lost_set:
                fut = self._futures.get(rid)
                if fut is not None and not fut.done():
                    fut.set_result(
                        HandoffOutput(rid=rid, snapshot=None, emitted=0)
                    )
            self._record_fault_event(
                affected,
                "engine_rebuilt",
                restored=restored,
                requeued=len(lost_set),
            )
            for rid in affected:
                emit_trace_event(rid, "engine_rebuilt")
            logger.warning(
                "engine rebuilt in-process after %s: %d request(s) "
                "restored, %d requeued",
                reason,
                restored,
                len(lost_set),
            )
            return True
        finally:
            timer.cancel()

    # --- engine thread ----------------------------------------------------
    def _run_handoff(self) -> None:
        """On the engine thread: drain, extract, resolve. Outputs that
        finish during the drain resolve normally; everything unfinished
        resolves with a HandoffOutput carrying its snapshot. Intake-queue
        stragglers (accepted before _draining flipped) resolve with a
        snapshot-less HandoffOutput — the worker requeues those whole."""
        self._handoff_requested = False
        results: List[HandoffOutput] = []
        try:
            outs: List[RequestOutput] = []
            snaps = self.core.extract_all(finished=outs)
            for out in outs:
                fut = self._futures.get(out.rid)
                if fut is not None and not fut.done():
                    fut.set_result(out)
            for snap in snaps:
                ho = HandoffOutput(
                    rid=snap.rid,
                    snapshot=snap,
                    emitted=len(snap.output_ids),
                )
                results.append(ho)
                fut = self._futures.get(snap.rid)
                if fut is not None and not fut.done():
                    fut.set_result(ho)
            while True:
                try:
                    item = self._intake.get_nowait()
                except queue.Empty:
                    break
                if item is None:
                    continue
                ho = HandoffOutput(rid=item[0], snapshot=None, emitted=0)
                results.append(ho)
                fut = self._futures.get(item[0])
                if fut is not None and not fut.done():
                    fut.set_result(ho)
        except Exception:  # noqa: BLE001 — handoff must never wedge shutdown
            logger.exception("engine handoff failed; aborting batch")
            self.core.abort_all("handoff_failed")
            for fut in list(self._futures.values()):
                if not fut.done():
                    fut.set_exception(RuntimeError("engine handoff failed"))
        finally:
            self._handoff_results = results
            ev = self._handoff_event
            if ev is not None:
                ev.set()

    def _run(self) -> None:
        while not self._stop:
            if self._handoff_requested:
                self._run_handoff()
            while True:  # marshalled calls (prefix export/ingest)
                try:
                    fn, call_fut = self._calls.get_nowait()
                except queue.Empty:
                    break
                try:
                    call_fut.set_result(fn())
                except Exception as exc:  # noqa: BLE001 — caller's error
                    call_fut.set_exception(exc)
            drained = False
            while True:
                try:
                    item = self._intake.get_nowait()
                except queue.Empty:
                    break
                if item is None:
                    continue
                (rid, prompt, messages, prompt_ids, params, snapshot, dl,
                 prefill_only, prio) = item
                try:
                    if snapshot is not None:
                        self.core.insert_request(snapshot, deadline_at=dl)
                    else:
                        self.core.add_request(
                            rid,
                            prompt=prompt,
                            messages=messages,
                            prompt_ids=prompt_ids,
                            params=params,
                            deadline_at=dl,
                            prefill_only=prefill_only,
                            priority=prio,
                        )
                    drained = True
                except Exception as exc:  # tokenization/validation error
                    fut = self._futures.get(rid)
                    if fut is not None and not fut.done():
                        fut.set_exception(exc)
            if not self.core.has_work and not drained:
                # Idle integrity sweep (weight audit / KV spot-check /
                # canary replay on their cadences; no-op at defaults).
                # Evidence of corruption routes into the same numerical
                # containment path a guard trip takes.
                try:
                    suspicion = self.core.maybe_idle_integrity()
                except Exception as idle_exc:  # noqa: BLE001 — replay tripped
                    suspicion = f"canary replay raised: {idle_exc}"
                if suspicion is not None:
                    if not self._recover_numerical(
                        DeviceFaultError(FAULT_NUMERICAL, suspicion)
                    ):
                        logger.error(
                            "numerical fault with no rebuild path: %s",
                            suspicion,
                        )
                self._wake.wait(timeout=0.02)
                self._wake.clear()
                continue
            try:
                for out in self.core.step():
                    self._numerical_probation.pop(out.rid, None)
                    fut = self._futures.get(out.rid)
                    if fut is not None and not fut.done():
                        fut.set_result(out)
            except Exception as exc:  # noqa: BLE001 — keep the loop alive
                reason = classify_failure(exc)
                if reason == FAULT_OOM and self._degrade_and_restore(exc):
                    continue
                if reason == FAULT_NUMERICAL and self._recover_numerical(exc):
                    continue
                if reason is not None and self.rebuild_core is not None:
                    if self._rebuild_after_fault(reason, exc):
                        continue
                logger.exception("engine step failed")
                # Fail all in-flight requests AND clear the core's batch:
                # re-stepping a half-updated batch would loop hot on the
                # same exception. The worker requeues the jobs.
                self.core.abort_all("error")
                # Drain the intake queue too: those requests' futures are
                # failed below, so adding them next iteration would
                # generate orphaned completions nobody is awaiting.
                while True:
                    try:
                        self._intake.get_nowait()
                    except queue.Empty:
                        break
                # Classified device faults keep their class on the way
                # out so the worker dead-letters with a precise
                # x-failure-reason; everything else is byte-identical to
                # the pre-containment path.
                failure: Exception = (
                    DeviceFaultError(reason, f"engine step failed: {exc}")
                    if reason is not None
                    else RuntimeError("engine step failed")
                )
                for fut in list(self._futures.values()):
                    if not fut.done():
                        fut.set_exception(failure)
        # Loop exit (shutdown): catch the host up so in-flight steps are
        # processed and deferred pages release — the last futures resolve
        # several iterations before the run-ahead pipeline fully lands,
        # and stopping mid-pipeline would strand refcounts.
        try:
            self.core._drain([])
        except Exception:  # noqa: BLE001 — best-effort cleanup
            logger.exception("drain on shutdown failed")
        self.core.stop_watchdog()
