"""Pipeline YAML schema tests (reference core/pipeline.py parity + fixes)."""

import pytest
from pydantic import ValidationError

from llmq_tpu.core.pipeline import PipelineConfig

SIMPLE = """
name: simple-test
stages:
  - name: stage1
    worker: dummy
  - name: stage2
    worker: dummy
"""

TRANSLATE = """
name: translate-format
stages:
  - name: translate
    worker: tpu
    config:
      model: some/model-9b
      prompt: "Translate to Dutch: {text}"
  - name: format
    worker: tpu
    config:
      model: some/model-2b
      prompt: "Format this translation nicely: {result}"
config:
  timeout_minutes: 60
"""


def test_load_simple():
    cfg = PipelineConfig.from_yaml_string(SIMPLE)
    assert cfg.name == "simple-test"
    assert [s.name for s in cfg.stages] == ["stage1", "stage2"]


def test_queue_names():
    cfg = PipelineConfig.from_yaml_string(SIMPLE)
    assert cfg.get_stage_queue_name("stage1") == "pipeline.simple-test.stage1"
    assert cfg.get_pipeline_results_queue_name() == "pipeline.simple-test.results"
    assert cfg.stage_queue_names() == [
        "pipeline.simple-test.stage1",
        "pipeline.simple-test.stage2",
    ]


def test_next_stage():
    cfg = PipelineConfig.from_yaml_string(SIMPLE)
    assert cfg.next_stage("stage1").name == "stage2"
    assert cfg.next_stage("stage2") is None
    with pytest.raises(KeyError):
        cfg.next_stage("nope")


def test_stage_templates():
    cfg = PipelineConfig.from_yaml_string(TRANSLATE)
    assert cfg.stages[0].prompt_template() == "Translate to Dutch: {text}"
    assert "{result}" in cfg.stages[1].prompt_template()


def test_invalid_names():
    with pytest.raises(ValidationError):
        PipelineConfig.from_yaml_string(
            "name: 'bad name!'\nstages:\n  - name: a\n    worker: dummy\n"
        )
    with pytest.raises(ValidationError):
        PipelineConfig.from_yaml_string(
            "name: ok\nstages:\n  - name: 'sp ace'\n    worker: dummy\n"
        )


def test_duplicate_stage_names():
    bad = """
name: p
stages:
  - name: s
    worker: dummy
  - name: s
    worker: dummy
"""
    with pytest.raises(ValidationError):
        PipelineConfig.from_yaml_string(bad)


def test_missing_file(tmp_path):
    with pytest.raises(FileNotFoundError):
        PipelineConfig.from_yaml_file(tmp_path / "nope.yaml")
