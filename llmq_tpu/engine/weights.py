"""HF checkpoint loading: safetensors → stacked param pytree, streamed.

The reference inherited weight loading from vLLM; here it's native. Reads a
HuggingFace model directory (config.json + *.safetensors), maps tensor names
onto the ``models/transformer.py`` layout, stacks per-layer weights on a
leading [L, ...] axis (for the scanned layer body), and **streams** them
onto the devices:

- Each stacked parameter is allocated directly on device (with its
  NamedSharding when a mesh is given) and filled one layer at a time via a
  donated ``dynamic_update_index_in_dim`` jit — host memory never holds
  more than one layer's tensor of one parameter.
- Large 2-D tensors (embeddings, lm_head) are read in bounded row chunks
  through safetensors' lazy ``get_slice`` and written into the device
  buffer the same way.

Peak host RSS during a load is therefore ~max(single tensor, chunk)
instead of the full checkpoint — the difference between a 72B bf16 load
needing ~145 GB of host RAM and needing well under 1 GB.

Name mapping (HF → ours):
    model.embed_tokens.weight            embed                 [V, H]
    model.layers.N.input_layernorm       layers.ln1[N]
    model.layers.N.self_attn.{q,k,v}_proj  layers.{q,k,v}_proj[N]  (transposed)
    model.layers.N.self_attn.o_proj      layers.o_proj[N]      (transposed)
    model.layers.N.post_attention_layernorm
        → layers.ln2[N] for llama/qwen (it is the pre-MLP norm there)
        → layers.post_attn_norm[N] for gemma2 (true post-attn norm)
    model.layers.N.pre_feedforward_layernorm   layers.ln2[N]   (gemma2)
    model.layers.N.post_feedforward_layernorm  layers.post_mlp_norm[N]
    model.layers.N.mlp.{gate,up,down}_proj     layers.*[N]     (transposed)
    model.norm.weight                    final_norm
    lm_head.weight                       lm_head               (transposed)
"""

from __future__ import annotations

import hashlib
import json
import logging
from functools import partial
from pathlib import Path
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from llmq_tpu.models.config import ModelConfig
from llmq_tpu.models.transformer import Params

logger = logging.getLogger(__name__)

# Row-chunk budget for streaming large 2-D tensors (bytes of source data).
_CHUNK_BYTES = 256 * 2**20

# numpy dtype name → safetensors storage tag (for detecting converting
# loads in big2d's chunk budget).
_NP_TO_ST_TAG = {
    "float64": "F64", "float32": "F32", "float16": "F16",
    "bfloat16": "BF16", "int64": "I64", "int32": "I32",
    "int16": "I16", "int8": "I8", "uint8": "U8", "bool": "BOOL",
}


def _open_checkpoint(model_path: Path) -> Dict[str, Any]:
    """Map tensor name → shard file across all safetensors shards."""
    from safetensors import safe_open

    index: Dict[str, Path] = {}
    index_file = model_path / "model.safetensors.index.json"
    if index_file.exists():
        weight_map = json.loads(index_file.read_text())["weight_map"]
        for name, fname in weight_map.items():
            index[name] = model_path / fname
    else:
        shards = sorted(model_path.glob("*.safetensors"))
        if not shards:
            raise FileNotFoundError(f"No *.safetensors under {model_path}")
        for shard in shards:
            with safe_open(shard, framework="np") as f:
                for name in f.keys():
                    index[name] = shard
    return index


class _TensorReader:
    """Lazily reads tensors from safetensors shards, one file handle each."""

    def __init__(self, model_path: Path) -> None:
        from safetensors import safe_open

        self._safe_open = safe_open
        self.index = _open_checkpoint(model_path)
        self._handles: Dict[Path, Any] = {}

    def _handle(self, name: str):
        path = self.index[name]
        handle = self._handles.get(path)
        if handle is None:
            handle = self._safe_open(path, framework="np")
            self._handles[path] = handle
        return handle

    def get(self, name: str) -> np.ndarray:
        return self._handle(name).get_tensor(name)

    def get_rows(self, name: str, lo: int, hi: int) -> np.ndarray:
        """Read rows [lo:hi) of a tensor without materializing the rest."""
        return self._handle(name).get_slice(name)[lo:hi]

    def dtype_info(self, name: str) -> tuple:
        """(itemsize, tag) of the tensor *as stored* — e.g. ``(4, "F32")``.
        An fp32 checkpoint loaded as bf16 still costs 4 host bytes per
        element while in flight. Unknown/old safetensors: assume fp32."""
        st_sizes = {
            "F64": 8, "F32": 4, "F16": 2, "BF16": 2, "F8_E4M3": 1,
            "F8_E5M2": 1, "I64": 8, "I32": 4, "I16": 2, "I8": 1,
            "U8": 1, "BOOL": 1,
        }
        try:
            dt = str(self._handle(name).get_slice(name).get_dtype()).upper()
            return st_sizes.get(dt, 4), dt
        except Exception:  # noqa: BLE001 — older safetensors: assume fp32
            return 4, "F32"

    def shape(self, name: str) -> tuple:
        return tuple(self._handle(name).get_slice(name).get_shape())

    def close(self) -> None:
        self._handles.clear()


def _np_dtype(dtype) -> np.dtype:
    return jnp.dtype(dtype)  # ml_dtypes covers bf16


@partial(jax.jit, donate_argnums=(0,), static_argnames=("axis",))
def _write_block(buf: jnp.ndarray, block: jnp.ndarray, start, *, axis: int):
    idx = [0] * buf.ndim
    idx[axis] = start
    return jax.lax.dynamic_update_slice(buf, block, tuple(idx))


@partial(jax.jit, donate_argnums=(0,), static_argnames=("ndim",))
def _write_block_at(buf: jnp.ndarray, block: jnp.ndarray, starts, *, ndim: int):
    """Multi-axis block write (expert stacks stream per (layer, expert))."""
    idx = tuple(starts) + (0,) * (buf.ndim - ndim)
    return jax.lax.dynamic_update_slice(buf, block, idx)


class _Streamer:
    """Allocates device buffers and fills them block-by-block in place.

    With a ``ledger`` dict, every streamed buffer also folds its
    device-bound host bytes (post conversion / quantization — the exact
    representation written to HBM) into a blake2b recorded under the
    stream name. The hash rides the existing per-block loop, so the
    bounded-RSS property is untouched: one block is hashed, shipped,
    and dropped before the next is read."""

    def __init__(
        self,
        mesh: Optional[Mesh],
        specs: Optional[Params],
        ledger: Optional[Dict[str, str]] = None,
    ) -> None:
        self.mesh = mesh
        self.specs = specs
        self.ledger = ledger

    def _sharding(self, name: str) -> Optional[NamedSharding]:
        if self.mesh is None or self.specs is None:
            return None
        node: Any = self.specs
        for part in name.split("."):
            if not isinstance(node, dict):
                break  # "<name>.q" reuses the weight's own spec (int8 path)
            node = node[part]
        return NamedSharding(self.mesh, node)

    def _alloc(self, shape, dtype, sharding) -> jnp.ndarray:
        fn = jax.jit(
            lambda: jnp.zeros(shape, dtype),
            out_shardings=sharding,
        )
        return fn()

    def _block_sharding(self, sharding, axes):
        """The full-buffer sharding with the streamed axes unsharded (a
        block spans only part of those axes, so it can't keep a sharded
        spec there; every other axis keeps its placement)."""
        if sharding is None:
            return None
        parts = list(sharding.spec) + [None] * 8
        for axis in axes:
            parts[axis] = None
        return NamedSharding(self.mesh, P(*parts[: len(sharding.spec)]))

    def stream(
        self,
        name: str,
        shape: tuple,
        dtype,
        blocks,  # iterable of (start, np.ndarray); int start → `axis`,
        #          tuple start → offsets along the leading axes
        *,
        axis: int = 0,
    ) -> jnp.ndarray:
        sharding = self._sharding(name)
        buf = self._alloc(shape, dtype, sharding)
        bsh_cache: dict = {}
        fold = (
            hashlib.blake2b(digest_size=16)
            if self.ledger is not None
            else None
        )
        for start, block in blocks:
            host = np.ascontiguousarray(block).astype(
                _np_dtype(dtype), copy=False
            )
            if fold is not None:
                fold.update(np.ascontiguousarray(host).tobytes())
            axes = tuple(range(len(start))) if isinstance(start, tuple) else (axis,)
            if axes not in bsh_cache:
                bsh_cache[axes] = self._block_sharding(sharding, axes)
            bsh = bsh_cache[axes]
            dev = (
                jax.device_put(host, bsh)
                if bsh is not None
                else jax.device_put(host)
            )
            if isinstance(start, tuple):
                buf = _write_block_at(buf, dev, start, ndim=len(start))
            else:
                buf = _write_block(buf, dev, start, axis=axis)
        if fold is not None:
            self.ledger[name] = fold.hexdigest()
        return buf


def load_checkpoint(
    model_path: str | Path,
    config: Optional[ModelConfig] = None,
    *,
    dtype=jnp.bfloat16,
    mesh: Optional[Mesh] = None,
    quantize: bool | str = False,
    checksum_ledger: Optional[Dict[str, str]] = None,
) -> Params:
    """Load an HF checkpoint directory into the stacked param layout.

    ``mesh`` enables sharded streaming: every parameter is allocated on
    the mesh with its ``parallel/sharding.py`` NamedSharding and filled
    in place, so neither the host nor any single device ever holds an
    unsharded copy. Without a mesh, buffers stream onto the default
    device (single-device use; tests).

    ``quantize`` (``--dtype int8``) quantizes the big matmul weights to
    symmetric per-channel int8 *while streaming* — blocks are quantized
    on the host and land on device already int8, so the full-precision
    copy never exists in HBM (the point: a ~9B bf16 model that can't fit
    a 16 GB chip loads at ~half the bytes). ``quantize="int4"``
    (``--dtype int4``) puts the LAYER matmul weights on the AWQ-style
    group rung instead — packed two-codes-per-byte with per-group
    scale/zero tensors, a QUARTER of the bf16 bytes — while the
    embedding table and LM head stay int8 (the logit end is the
    precision-sensitive one). ``dtype`` remains the compute/scale
    dtype. See ``models/quant.py``.

    ``checksum_ledger`` (integrity plane): a dict the load fills with
    ``{stream_name: blake2b-16 hex}`` over each streamed buffer's
    device-bound bytes — computed once, per block, while the data is in
    flight anyway (all dtypes: bf16, int8, packed int4 all hash as
    their stored bytes). The ledger is the load-time provenance record
    two loads of the same checkpoint at the same dtype compare by; the
    engine's *device-side* baseline (``engine/integrity.py``) is what
    idle audits re-verify, since post-load layout optimization
    relocates buffers without changing their logical bytes.
    """
    from llmq_tpu.models import quant as qm

    model_path = Path(model_path)
    if config is None:
        config = ModelConfig.from_pretrained(model_path)
    reader = _TensorReader(model_path)
    L = config.num_layers
    np_dtype = _np_dtype(dtype)
    quant_mode = (
        "int4" if str(quantize).lower() == "int4"
        else ("int8" if quantize else None)
    )

    specs = None
    if mesh is not None:
        from llmq_tpu.parallel.mesh import TP_AXIS
        from llmq_tpu.parallel.sharding import param_pspecs

        specs = param_pspecs(config, int(mesh.shape.get(TP_AXIS, 1)))
    streamer = _Streamer(mesh, specs, ledger=checksum_ledger)

    def _finish_quant(buf, scales: np.ndarray, name: str, *, row_wise: bool):
        """Pair an int8 device buffer with its host-accumulated scales.
        The scale keeps the surviving axes of the weight's spec: drop the
        reduced axis (contraction for weights, features for embed)."""
        weight_spec = streamer._sharding(name + ".q")
        host = scales.astype(np_dtype)
        if weight_spec is None:
            return {"q": buf, "scale": jax.device_put(host)}
        parts = list(weight_spec.spec)
        parts = parts[:-1] if row_wise else parts[:-2] + parts[-1:]
        sdev = jax.device_put(host, NamedSharding(mesh, P(*parts)))
        return {"q": buf, "scale": sdev}

    def _np_quant(arr: np.ndarray, axis: int):
        """Host-side symmetric int8 quantization of one block."""
        a32 = np.asarray(arr, np.float32)
        amax = np.abs(a32).max(axis=axis)
        scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
        q = np.clip(
            np.rint(a32 / np.expand_dims(scale, axis)), -127, 127
        ).astype(np.int8)
        return q, scale

    def _np_quant_int4(arr: np.ndarray):
        """Host-side int4 group quantization of one [.., K, N] block —
        the numpy mirror of ``quant.quantize_array_int4`` (np.rint and
        jnp.round both round half to even, so device and streamed loads
        produce identical codes)."""
        a32 = np.asarray(arr, np.float32)
        k = a32.shape[-2]
        if k % 2:
            raise ValueError(f"int4 needs an even contraction dim, got {k}")
        group = qm.int4_group(k)
        g = k // group
        ag = a32.reshape(*a32.shape[:-2], g, group, a32.shape[-1])
        amin = ag.min(axis=-2)
        amax = ag.max(axis=-2)
        scale = np.where(amax > amin, (amax - amin) / 15.0, 1.0).astype(
            np.float32
        )
        zero = np.rint(-amin / scale).astype(np.float32)
        q = np.clip(
            np.rint(ag / scale[..., None, :] + zero[..., None, :]), 0, 15
        ).astype(np.uint8)
        q = q.reshape(a32.shape)
        packed = q[..., 0::2, :] | (q[..., 1::2, :] << 4)
        return packed, scale, zero

    def _finish_quant_int4(buf, scales: np.ndarray, zeros: np.ndarray, name: str):
        """Pair a packed-uint8 device buffer with its group scale/zero
        tensors. q keeps the weight's own spec (the packed axis IS the
        contraction axis); scale/zero replicate their group axis — same
        layout ``quant.quantized_specs`` produces."""
        weight_spec = streamer._sharding(name + ".q")
        s_host = scales.astype(np_dtype)
        z_host = zeros.astype(np_dtype)
        if weight_spec is None:
            return {
                "q": buf,
                "scale": jax.device_put(s_host),
                "zero": jax.device_put(z_host),
            }
        parts = list(weight_spec.spec) + [None] * (
            buf.ndim - len(weight_spec.spec)
        )
        sz = NamedSharding(mesh, P(*(parts[:-2] + [None] + parts[-1:])))
        return {
            "q": buf,
            "scale": jax.device_put(s_host, sz),
            "zero": jax.device_put(z_host, sz),
        }

    def stacked(our_name: str, fmt: str, *, transpose: bool = False):
        """Stream layer tensors into a [L, ...] device stack."""
        shape0 = reader.shape(fmt.format(i=0))
        if transpose:
            shape0 = shape0[::-1]
        quant = bool(quant_mode) and our_name in qm.QUANTIZED_LAYER_KEYS
        int4 = quant and quant_mode == "int4"
        if int4:
            full = (L, *shape0[:-2], shape0[-2] // 2, shape0[-1])
            g = shape0[-2] // qm.int4_group(shape0[-2])
            scales = np.ones((L, *shape0[:-2], g, shape0[-1]), np.float32)
            zeros = np.zeros_like(scales)
        else:
            full = (L, *shape0)
            scales = (
                np.ones((L, *shape0[:-2], shape0[-1]), np.float32)
                if quant
                else None
            )
            zeros = None

        def blocks():
            for i in range(L):
                arr = reader.get(fmt.format(i=i))
                if transpose:
                    arr = arr.T
                if int4:
                    arr, s, z = _np_quant_int4(arr)
                    scales[i] = s
                    zeros[i] = z
                elif quant:
                    arr, s = _np_quant(arr, axis=-2)
                    scales[i] = s
                yield i, arr[None]

        buf = streamer.stream(
            f"layers.{our_name}" + (".q" if quant else ""),
            full,
            (jnp.uint8 if int4 else jnp.int8) if quant else dtype,
            blocks(),
        )
        if not quant:
            return buf
        if int4:
            return _finish_quant_int4(buf, scales, zeros, f"layers.{our_name}")
        return _finish_quant(buf, scales, f"layers.{our_name}", row_wise=False)

    def big2d(our_name: str, hf_name: str, *, transpose: bool = False):
        """Stream a large 2-D tensor in bounded row chunks."""
        rows, cols = reader.shape(hf_name)
        # Budget by stored + target element sizes whenever the DTYPES
        # differ (not just the sizes — fp16→bf16 is same-size but still
        # copies): a converting load briefly holds BOTH the stored rows
        # and the converted copy, so chunking by either size alone
        # overshoots the documented _CHUNK_BYTES peak.
        stored_size, stored_tag = reader.dtype_info(hf_name)
        target = np.dtype(np_dtype)
        target_tag = _NP_TO_ST_TAG.get(target.name)
        converts = stored_tag != target_tag
        itemsize = stored_size + target.itemsize if converts else target.itemsize
        chunk = max(1, _CHUNK_BYTES // max(1, cols * itemsize))
        shape = (cols, rows) if transpose else (rows, cols)
        axis = 1 if transpose else 0
        # Top-level tensors stay on the int8 rung under either quantize
        # mode — see the load_checkpoint docstring.
        quant = bool(quant_mode) and our_name in qm.QUANTIZED_TOP_KEYS
        # embed quantizes per ROW (lookup axis); lm_head (streamed
        # transposed, [H, V]) per output column — both reduce over the
        # stored tensor's column axis, so the block math is identical.
        scales = np.ones((rows,), np.float32) if quant else None

        def blocks():
            for lo in range(0, rows, chunk):
                hi = min(rows, lo + chunk)
                arr = reader.get_rows(hf_name, lo, hi)
                if quant:
                    arr, s = _np_quant(arr, axis=1)
                    scales[lo:hi] = s
                yield lo, arr.T if transpose else arr

        buf = streamer.stream(
            our_name + (".q" if quant else ""),
            shape,
            jnp.int8 if quant else dtype,
            blocks(),
            axis=axis,
        )
        if not quant:
            return buf
        return _finish_quant(buf, scales, our_name, row_wise=not transpose)

    def has(name: str) -> bool:
        return name in reader.index

    layers: Params = {}
    layers["ln1"] = stacked("ln1", "model.layers.{i}.input_layernorm.weight")
    if config.post_norms:  # gemma2 4-norm layout
        layers["post_attn_norm"] = stacked(
            "post_attn_norm", "model.layers.{i}.post_attention_layernorm.weight"
        )
        layers["ln2"] = stacked(
            "ln2", "model.layers.{i}.pre_feedforward_layernorm.weight"
        )
        layers["post_mlp_norm"] = stacked(
            "post_mlp_norm", "model.layers.{i}.post_feedforward_layernorm.weight"
        )
    else:
        layers["ln2"] = stacked(
            "ln2", "model.layers.{i}.post_attention_layernorm.weight"
        )
    for ours, theirs in (
        ("q_proj", "self_attn.q_proj"),
        ("k_proj", "self_attn.k_proj"),
        ("v_proj", "self_attn.v_proj"),
        ("o_proj", "self_attn.o_proj"),
    ):
        layers[ours] = stacked(
            ours, f"model.layers.{{i}}.{theirs}.weight", transpose=True
        )
    if config.num_experts:
        E = config.num_experts

        def expert_stacked(our_name: str, fmt: str):
            """Stream a [L, E, in, out] expert stack one (layer, expert)
            tensor at a time — host RSS stays ~1 expert tensor."""
            shape0 = reader.shape(fmt.format(i=0, e=0))[::-1]  # transposed
            quant = bool(quant_mode) and our_name in qm.QUANTIZED_LAYER_KEYS
            int4 = quant and quant_mode == "int4"
            if int4:
                full = (L, E, shape0[-2] // 2, shape0[-1])
                g = shape0[-2] // qm.int4_group(shape0[-2])
                scales = np.ones((L, E, g, shape0[-1]), np.float32)
                zeros = np.zeros_like(scales)
            else:
                full = (L, E, *shape0)
                scales = (
                    np.ones((L, E, shape0[-1]), np.float32) if quant else None
                )
                zeros = None

            def blocks():
                for i in range(L):
                    for e in range(E):
                        arr = reader.get(fmt.format(i=i, e=e)).T
                        if int4:
                            arr, s, z = _np_quant_int4(arr)
                            scales[i, e] = s
                            zeros[i, e] = z
                        elif quant:
                            arr, s = _np_quant(arr, axis=-2)
                            scales[i, e] = s
                        yield (i, e), arr[None, None]

            buf = streamer.stream(
                f"layers.{our_name}" + (".q" if quant else ""),
                full,
                (jnp.uint8 if int4 else jnp.int8) if quant else dtype,
                blocks(),
            )
            if not quant:
                return buf
            if int4:
                return _finish_quant_int4(
                    buf, scales, zeros, f"layers.{our_name}"
                )
            return _finish_quant(
                buf, scales, f"layers.{our_name}", row_wise=False
            )

        layers["router"] = stacked(
            "router", "model.layers.{i}.mlp.gate.weight", transpose=True
        )
        for ours, theirs in (
            ("expert_gate_proj", "gate_proj"),
            ("expert_up_proj", "up_proj"),
            ("expert_down_proj", "down_proj"),
        ):
            layers[ours] = expert_stacked(
                ours, f"model.layers.{{i}}.mlp.experts.{{e}}.{theirs}.weight"
            )
        if config.shared_expert_intermediate_size:
            for ours, theirs in (
                ("shared_gate_proj", "shared_expert.gate_proj"),
                ("shared_up_proj", "shared_expert.up_proj"),
                ("shared_down_proj", "shared_expert.down_proj"),
                ("shared_expert_gate", "shared_expert_gate"),
            ):
                layers[ours] = stacked(
                    ours, f"model.layers.{{i}}.mlp.{theirs}.weight",
                    transpose=True,
                )
    else:
        for ours, theirs in (
            ("gate_proj", "mlp.gate_proj"),
            ("up_proj", "mlp.up_proj"),
            ("down_proj", "mlp.down_proj"),
        ):
            layers[ours] = stacked(
                ours, f"model.layers.{{i}}.{theirs}.weight", transpose=True
            )
    if config.attention_bias:
        for ours, theirs in (
            ("q_bias", "self_attn.q_proj"),
            ("k_bias", "self_attn.k_proj"),
            ("v_bias", "self_attn.v_proj"),
        ):
            layers[ours] = stacked(
                ours, f"model.layers.{{i}}.{theirs}.bias"
            )
    if config.qk_norm:
        layers["q_norm"] = stacked(
            "q_norm", "model.layers.{i}.self_attn.q_norm.weight"
        )
        layers["k_norm"] = stacked(
            "k_norm", "model.layers.{i}.self_attn.k_norm.weight"
        )

    params: Params = {
        "embed": big2d("embed", "model.embed_tokens.weight"),
        "final_norm": streamer.stream(
            "final_norm",
            reader.shape("model.norm.weight"),
            dtype,
            [(0, reader.get("model.norm.weight"))],
        ),
        "layers": layers,
    }
    if not config.tie_word_embeddings and has("lm_head.weight"):
        params["lm_head"] = big2d("lm_head", "lm_head.weight", transpose=True)

    reader.close()
    n_params = sum(x.size for x in jax.tree.leaves(params))
    logger.info(
        "Loaded %s: %.2fB params as %s", model_path, n_params / 1e9, dtype
    )
    if checksum_ledger is not None:
        logger.info(
            "load checksums recorded for %d streamed tensor(s)",
            len(checksum_ledger),
        )
    return params
