"""TCP broker: a self-hosted broker daemon + asyncio client.

Plays the role RabbitMQ plays for the reference (external broker process all
workers/CLIs connect to — SURVEY.md §1 L0), with no external dependency:
``llmq-tpu broker serve`` starts the daemon, every other process points
``LLMQ_BROKER_URL=tcp://host:port`` at it. Multi-host deployments (one broker
node, N TPU worker hosts) work exactly like the reference's SLURM recipes.

Wire protocol — length-prefixed JSON frames (4-byte big-endian size + UTF-8
JSON):

  client → server: {op, req_id, ...}   ops: declare publish consume cancel
                                            get settle stats purge ping
  server → client: {type:"reply", req_id, ok, ...}
                   {type:"deliver", queue, tag, message_id, body,
                    delivery_count, headers}

Delivery/settlement: the server tracks per-connection consumers; a dropped
connection requeues its unacked messages (at-least-once, like an AMQP channel
close). Durability: an append-only journal (publish/settle records) replayed
on startup, compacted when mostly settled.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import struct
import uuid
from pathlib import Path
from typing import Any, Dict, Optional, Set

from llmq_tpu.broker.base import (
    Broker,
    DeliveredMessage,
    MessageHandler,
    decode_body,
    encode_body,
    new_message_id,
)
from llmq_tpu.broker.memory import BrokerCore
from llmq_tpu.core.models import QueueStats
from llmq_tpu.utils.aio import reap, reap_all, spawn

logger = logging.getLogger(__name__)

MAX_FRAME = 64 * 1024 * 1024
_HDR = struct.Struct(">I")


async def read_frame(reader: asyncio.StreamReader) -> Optional[Dict[str, Any]]:
    try:
        hdr = await reader.readexactly(_HDR.size)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    (size,) = _HDR.unpack(hdr)
    if size > MAX_FRAME:
        raise ValueError(f"Frame too large: {size}")
    try:
        payload = await reader.readexactly(size)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    return json.loads(payload.decode("utf-8"))


def write_frame(writer: asyncio.StreamWriter, obj: Dict[str, Any]) -> None:
    payload = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    writer.write(_HDR.pack(len(payload)) + payload)


# ---------------------------------------------------------------------------
# Server
# ---------------------------------------------------------------------------


class BrokerServer:
    """The broker daemon: BrokerCore + TCP transport + journal durability."""

    def __init__(
        self,
        host: str = "0.0.0.0",
        port: int = 5672,
        *,
        persist_dir: Optional[str | os.PathLike] = None,
    ) -> None:
        self.host = host
        self.port = port
        self.core = BrokerCore()
        self.persist_dir = Path(persist_dir) if persist_dir else None
        self._journal_file = None
        self._journal_ops = 0
        self._server: Optional[asyncio.AbstractServer] = None
        # Live client connections; stop() closes them so a daemon restart
        # actually severs sessions (clients then requeue/reconnect).
        self._conn_writers: set = set()
        # (tag, message_id) -> unsettled DeliveredMessage awaiting client verdict
        self._pending_settles: Dict[tuple, DeliveredMessage] = {}
        # Journal consistency for state transitions that happen inside the core:
        self.core.on_dead_letter = self._journal_dead_letter
        self.core.on_redeliver = self._journal_redeliver

    # --- durability -------------------------------------------------------
    def _journal_path(self) -> Path:
        assert self.persist_dir is not None
        return self.persist_dir / "journal.jsonl"

    def _load_journal(self) -> None:
        """Replay the journal into the core. Live set is keyed by
        ``(queue, message_id)`` so a message's dead-letter copy (same id,
        ``.failed`` queue) is tracked independently of the original."""
        if self.persist_dir is None:
            return
        self.persist_dir.mkdir(parents=True, exist_ok=True)
        path = self._journal_path()
        if not path.exists():
            return
        live: Dict[tuple, Dict[str, Any]] = {}
        with path.open() as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                op = rec.get("op")
                key = (rec.get("queue"), rec.get("message_id"))
                if op == "publish":
                    live[key] = rec
                elif op == "ack":
                    live.pop(key, None)
                elif op == "redeliver":
                    if key in live:
                        live[key]["delivery_count"] = (
                            live[key].get("delivery_count", 0) + 1
                        )
        for rec in live.values():
            self.core.publish(
                rec["queue"],
                decode_body(rec),
                message_id=rec["message_id"],
                headers=rec.get("headers", {}),
                delivery_count=rec.get("delivery_count", 0),
            )
        logger.info("Journal replay: %d live messages restored", len(live))
        self._compact_journal(live)

    def _compact_journal(self, live: Dict[tuple, Dict[str, Any]]) -> None:
        path = self._journal_path()
        tmp = path.with_suffix(".tmp")
        with tmp.open("w") as f:
            for rec in live.values():
                f.write(json.dumps(rec, separators=(",", ":")) + "\n")
        tmp.replace(path)
        self._journal_ops = 0

    # Compact once this many ops accumulate past the last compaction.
    JOURNAL_COMPACT_EVERY = 100_000

    def _journal(self, rec: Dict[str, Any]) -> None:
        if self.persist_dir is None:
            return
        if self._journal_file is None:
            self._journal_file = self._journal_path().open("a")
        self._journal_file.write(json.dumps(rec, separators=(",", ":")) + "\n")
        self._journal_file.flush()
        self._journal_ops += 1
        if self._journal_ops >= self.JOURNAL_COMPACT_EVERY:
            self._compact_from_core()

    def _compact_from_core(self) -> None:
        """Rewrite the journal from live broker state (bounds journal growth
        on long-running daemons; cheap relative to 100k journal writes)."""
        if self.persist_dir is None:
            return
        live: Dict[tuple, Dict[str, Any]] = {}
        for qname, q in self.core.queues.items():
            for msg in list(q.ready) + [m for m, _ in q.unacked.values()]:
                live[(qname, msg.message_id)] = {
                    "op": "publish",
                    "queue": qname,
                    "message_id": msg.message_id,
                    **encode_body(msg.body),
                    "headers": msg.headers,
                    "delivery_count": msg.delivery_count,
                }
        if self._journal_file is not None:
            self._journal_file.close()
            self._journal_file = None
        self._compact_journal(live)
        logger.info("Journal compacted: %d live messages", len(live))

    def _journal_dead_letter(self, queue: str, msg) -> None:
        """Core moved ``msg`` from ``queue`` to ``queue.failed``: ack the
        original and journal the DLQ copy so restart state matches."""
        headers = dict(msg.headers)
        headers["x-death-queue"] = queue
        headers["x-delivery-count"] = msg.delivery_count
        self._journal({"op": "ack", "queue": queue, "message_id": msg.message_id})
        self._journal(
            {
                "op": "publish",
                "queue": queue + ".failed",
                "message_id": msg.message_id,
                **encode_body(msg.body),
                "headers": headers,
            }
        )

    def _journal_redeliver(self, queue: str, msg) -> None:
        self._journal(
            {"op": "redeliver", "queue": queue, "message_id": msg.message_id}
        )

    # --- lifecycle --------------------------------------------------------
    async def start(self) -> None:
        self._load_journal()
        self._server = await asyncio.start_server(
            self._handle_connection, self.host, self.port
        )
        addrs = ", ".join(str(s.getsockname()) for s in self._server.sockets)
        logger.info("llmq-tpu broker listening on %s", addrs)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        for writer in list(self._conn_writers):
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionResetError, BrokenPipeError, OSError):
                pass
        self._conn_writers.clear()
        if self._journal_file is not None:
            self._journal_file.close()
            self._journal_file = None

    async def serve_forever(self) -> None:
        await self.start()
        assert self._server is not None
        async with self._server:
            await self._server.serve_forever()

    # --- connection handling ----------------------------------------------
    async def _handle_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        conn_tags: list[str] = []
        self._conn_writers.add(writer)
        write_lock = asyncio.Lock()

        async def send(obj: Dict[str, Any]) -> None:
            async with write_lock:
                write_frame(writer, obj)
                await writer.drain()

        try:
            while True:
                try:
                    req = await read_frame(reader)
                except (ValueError, json.JSONDecodeError) as exc:
                    # Not our protocol (or corrupt frame): drop the connection.
                    logger.warning("Dropping connection on bad frame: %s", exc)
                    break
                if req is None:
                    break
                try:
                    await self._handle_request(req, send, conn_tags)
                except Exception as exc:  # noqa: BLE001 — reply, don't die
                    await send(
                        {
                            "type": "reply",
                            "req_id": req.get("req_id"),
                            "ok": False,
                            "error": f"{type(exc).__name__}: {exc}",
                        }
                    )
        finally:
            self._conn_writers.discard(writer)
            dead = set(conn_tags)
            for key in [k for k in self._pending_settles if k[0] in dead]:
                self._pending_settles.pop(key, None)
            for tag in conn_tags:
                self.core.remove_consumer(tag)
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionResetError, BrokenPipeError):
                pass

    async def _handle_request(self, req, send, conn_tags) -> None:
        op = req.get("op")
        req_id = req.get("req_id")

        def reply(**kw) -> Dict[str, Any]:
            return {"type": "reply", "req_id": req_id, "ok": True, **kw}

        if op == "ping":
            await send(reply())
        elif op == "declare":
            self.core.declare(
                req["queue"],
                ttl_ms=req.get("ttl_ms"),
                max_redeliveries=req.get("max_redeliveries"),
            )
            await send(reply())
        elif op == "publish":
            message_id = req.get("message_id") or new_message_id()
            self._journal(
                {
                    "op": "publish",
                    "queue": req["queue"],
                    "message_id": message_id,
                    "body": req["body"],
                    **({"enc": req["enc"]} if req.get("enc") else {}),
                    "headers": req.get("headers", {}),
                }
            )
            self.core.publish(
                req["queue"],
                decode_body(req),
                message_id=message_id,
                headers=req.get("headers"),
            )
            await send(reply(message_id=message_id))
        elif op == "consume":
            tag = f"tcp-{uuid.uuid4().hex[:12]}"
            queue = req["queue"]

            async def deliver(message: DeliveredMessage) -> None:
                # Forward to the client; settlement comes back as a frame.
                self._pending_settles[(tag, message.message_id)] = (queue, message)
                try:
                    await send(
                        {
                            "type": "deliver",
                            "queue": queue,
                            "tag": tag,
                            "message_id": message.message_id,
                            **encode_body(message.body),
                            "delivery_count": message.delivery_count,
                            "headers": message.headers,
                        }
                    )
                except (ConnectionResetError, BrokenPipeError):
                    self._pending_settles.pop((tag, message.message_id), None)
                    await message.reject(requeue=True)

            self.core.add_consumer(queue, tag, deliver, req.get("prefetch", 1))
            conn_tags.append(tag)
            await send(reply(tag=tag))
        elif op == "cancel":
            tag = req["tag"]
            # requeue=False is basic.cancel: deliveries stop, but this
            # connection's unacked messages stay settleable (drain-with-
            # handoff acks them after republishing). The tag stays in
            # conn_tags so the disconnect cleanup requeues whatever is
            # still unacked at close.
            self.core.remove_consumer(
                tag, requeue_in_flight=bool(req.get("requeue", True))
            )
            await send(reply())
        elif op == "settle":
            key = (req["tag"], req["message_id"])
            entry = self._pending_settles.pop(key, None)
            if req["tag"].startswith("get-") and req["tag"] in conn_tags:
                conn_tags.remove(req["tag"])  # one-shot get consumer settled
            if entry is not None:
                queue, message = entry
                if req["verb"] == "ack":
                    self._journal(
                        {
                            "op": "ack",
                            "queue": queue,
                            "message_id": req["message_id"],
                        }
                    )
                    await message.ack()
                else:
                    requeue = req.get("requeue", False)
                    if not requeue:
                        self._journal(
                            {
                                "op": "ack",
                                "queue": queue,
                                "message_id": req["message_id"],
                            }
                        )
                    await message.reject(requeue=requeue)
            await send(reply())
        elif op == "get":
            tag = f"get-{uuid.uuid4().hex[:12]}"
            message = self.core.get_one(req["queue"], tag=tag)
            if message is None:
                await send(reply(empty=True))
            else:
                # Track like a consumer so a client disconnect requeues it.
                conn_tags.append(tag)
                self._pending_settles[(tag, message.message_id)] = (
                    req["queue"],
                    message,
                )
                await send(
                    reply(
                        empty=False,
                        tag=tag,
                        message_id=message.message_id,
                        **encode_body(message.body),
                        delivery_count=message.delivery_count,
                        headers=message.headers,
                    )
                )
        elif op == "stats":
            await send(reply(stats=self.core.stats(req["queue"]).model_dump()))
        elif op == "purge":
            purged_ids = self.core.purge(req["queue"])
            for mid in purged_ids:
                self._journal(
                    {"op": "ack", "queue": req["queue"], "message_id": mid}
                )
            await send(reply(purged=len(purged_ids)))
        elif op == "delete":
            # Queue deletion drops ready AND unacked messages — journal an
            # ack per dropped id so a restart doesn't resurrect them onto
            # a queue that no longer exists.
            dropped_ids = self.core.delete(req["queue"])
            for mid in dropped_ids:
                self._journal(
                    {"op": "ack", "queue": req["queue"], "message_id": mid}
                )
            for key in [
                k
                for k, (q, _) in self._pending_settles.items()
                if q == req["queue"]
            ]:
                self._pending_settles.pop(key, None)
            await send(reply(deleted=len(dropped_ids)))
        else:
            await send(
                {"type": "reply", "req_id": req_id, "ok": False, "error": f"bad op {op!r}"}
            )


# ---------------------------------------------------------------------------
# Client
# ---------------------------------------------------------------------------


class TcpBroker(Broker):
    """Client side: implements the Broker interface over one TCP connection."""

    def __init__(self, url: str) -> None:
        self.url = url
        rest = url.split("://", 1)[1]
        hostport = rest.split("/", 1)[0]
        host, _, port = hostport.partition(":")
        self.host = host or "127.0.0.1"
        self.port = int(port or 5672)
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._recv_task: Optional[asyncio.Task] = None
        self._replies: Dict[str, asyncio.Future] = {}
        self._handlers: Dict[str, MessageHandler] = {}
        # Deliveries can land before consume() has registered the handler
        # (the server starts dispatching the moment the consumer exists);
        # buffer them per-tag until the handler is in place.
        self._undispatched: Dict[str, list] = {}
        self._write_lock: Optional[asyncio.Lock] = None
        self._req_seq = 0
        self._lost = False
        # Strong refs to in-flight handler tasks: the loop only keeps weak
        # ones, so a naked ensure_future could be GC'd mid-delivery.
        self._handler_tasks: Set[asyncio.Task] = set()

    @property
    def is_connected(self) -> bool:
        return self._writer is not None and not self._lost

    async def connect(self) -> None:
        if self._writer is not None:
            return
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port, limit=MAX_FRAME
        )
        self._lost = False
        self._write_lock = asyncio.Lock()
        self._recv_task = asyncio.ensure_future(self._recv_loop())
        await self._request({"op": "ping"})

    async def close(self) -> None:
        await reap(self._recv_task, label="tcp recv loop")
        self._recv_task = None
        # Unfinished deliveries are cancelled; the server requeues anything
        # unacked once the connection drops, so this is loss-free.
        await reap_all(self._handler_tasks, label="tcp handler task")
        if self._writer is not None:
            self._writer.close()
            try:
                await self._writer.wait_closed()
            except (ConnectionResetError, BrokenPipeError):
                pass
        self._reader = self._writer = None
        self._handlers.clear()

    async def _recv_loop(self) -> None:
        reader = self._reader
        assert reader is not None
        while True:
            try:
                frame = await read_frame(reader)
            except (ValueError, json.JSONDecodeError) as exc:
                logger.error("Protocol error from broker: %s", exc)
                frame = None
            if frame is None:
                self._lost = True
                for fut in self._replies.values():
                    if not fut.done():
                        fut.set_exception(ConnectionError("broker connection lost"))
                self._replies.clear()
                self._notify_connection_lost()
                return
            ftype = frame.get("type")
            if ftype == "reply":
                fut = self._replies.pop(frame.get("req_id"), None)
                if fut is not None and not fut.done():
                    if frame.get("ok"):
                        fut.set_result(frame)
                    else:
                        fut.set_exception(
                            RuntimeError(frame.get("error", "broker error"))
                        )
            elif ftype == "deliver":
                tag = frame["tag"]
                handler = self._handlers.get(tag)
                if handler is not None:
                    message = self._delivered_from(frame)
                    spawn(
                        self._run_handler(handler, message),
                        registry=self._handler_tasks,
                        name=f"tcp-handler:{tag}",
                    )
                else:
                    self._undispatched.setdefault(tag, []).append(frame)

    async def _run_handler(
        self, handler: MessageHandler, message: DeliveredMessage
    ) -> None:
        try:
            await handler(message)
        except Exception:  # noqa: BLE001
            await message.reject(requeue=True)

    def _delivered_from(self, frame: Dict[str, Any]) -> DeliveredMessage:
        tag = frame["tag"]
        message_id = frame["message_id"]

        async def settle(verb: str, requeue: bool) -> None:
            try:
                await self._request(
                    {
                        "op": "settle",
                        "tag": tag,
                        "message_id": message_id,
                        "verb": verb,
                        "requeue": requeue,
                    }
                )
            except ConnectionError:
                pass  # server requeues in-flight messages on disconnect

        return DeliveredMessage(
            decode_body(frame),
            message_id,
            delivery_count=frame.get("delivery_count", 0),
            headers=frame.get("headers", {}),
            _settle=settle,
        )

    async def _request(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        if self._writer is None or self._write_lock is None or self._lost:
            raise ConnectionError("Broker is not connected")
        self._req_seq += 1
        req_id = f"r{self._req_seq}"
        obj = {**obj, "req_id": req_id}
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._replies[req_id] = fut
        try:
            async with self._write_lock:
                write_frame(self._writer, obj)
                await self._writer.drain()
        except OSError as exc:
            # Write-side detection: the recv loop may not have noticed yet.
            self._replies.pop(req_id, None)
            if not self._lost:
                self._lost = True
                self._notify_connection_lost()
            raise ConnectionError(f"broker connection lost: {exc}") from exc
        return await fut

    # --- Broker interface -------------------------------------------------
    async def declare_queue(
        self,
        name: str,
        *,
        durable: bool = True,
        ttl_ms: Optional[int] = None,
        max_redeliveries: Optional[int] = None,
    ) -> None:
        await self._request(
            {
                "op": "declare",
                "queue": name,
                "ttl_ms": ttl_ms,
                "max_redeliveries": max_redeliveries,
            }
        )

    async def publish(
        self,
        queue: str,
        body: bytes,
        *,
        message_id: Optional[str] = None,
        headers: Optional[Dict[str, Any]] = None,
    ) -> None:
        await self._request(
            {
                "op": "publish",
                "queue": queue,
                **encode_body(body),
                "message_id": message_id,
                "headers": headers or {},
            }
        )

    async def consume(
        self, queue: str, handler: MessageHandler, *, prefetch: int = 1
    ) -> str:
        reply = await self._request(
            {"op": "consume", "queue": queue, "prefetch": prefetch}
        )
        tag = reply["tag"]
        self._handlers[tag] = handler
        for frame in self._undispatched.pop(tag, []):
            message = self._delivered_from(frame)
            spawn(
                self._run_handler(handler, message),
                registry=self._handler_tasks,
                name=f"tcp-handler:{tag}",
            )
        return tag

    async def cancel(self, consumer_tag: str, *, requeue: bool = True) -> None:
        self._handlers.pop(consumer_tag, None)
        await self._request(
            {"op": "cancel", "tag": consumer_tag, "requeue": requeue}
        )

    async def get(self, queue: str) -> Optional[DeliveredMessage]:
        reply = await self._request({"op": "get", "queue": queue})
        if reply.get("empty"):
            return None
        return self._delivered_from(reply)

    async def stats(self, queue: str) -> QueueStats:
        reply = await self._request({"op": "stats", "queue": queue})
        return QueueStats(**reply["stats"])

    async def purge(self, queue: str) -> int:
        reply = await self._request({"op": "purge", "queue": queue})
        return int(reply.get("purged", 0))

    async def delete_queue(self, name: str) -> None:
        await self._request({"op": "delete", "queue": name})
