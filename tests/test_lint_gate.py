"""CI lint gate: the analyzer must run clean over the shipped package.

This is the enforcement half of the static pass — any PR introducing an
orphan task, an unsettled message path, a blocking call in a coroutine, a
cancellation-swallowing loop, or a host sync in jitted code fails here
with the exact file:line:rule, before review.
"""

from pathlib import Path

import pytest

from llmq_tpu.analysis import analyze_paths

PACKAGE_ROOT = Path(__file__).parent.parent / "llmq_tpu"


@pytest.mark.unit
def test_package_has_no_error_violations():
    violations = analyze_paths([str(PACKAGE_ROOT)])
    errors = [v for v in violations if v.severity == "error"]
    assert not errors, "new lint violations:\n" + "\n".join(
        v.render() for v in errors
    )


@pytest.mark.unit
def test_package_warning_budget():
    # Warnings don't fail the build, but they must not accumulate silently:
    # bump this budget only with a pragma-level justification in the diff.
    violations = analyze_paths([str(PACKAGE_ROOT)])
    warnings = [v for v in violations if v.severity == "warning"]
    assert len(warnings) <= 0, "lint warnings grew:\n" + "\n".join(
        v.render() for v in warnings
    )
