"""Build a small but fully genuine HF checkpoint directory offline.

Produces everything a real hub download has: config.json, sharded
safetensors with index, a trained BPE tokenizer (tokenizer.json), and a
chat template — so the HFTokenizer + load_checkpoint + chat-template path
is exercised exactly as it would be with a hub model, without network.

Usable as a pytest helper and as a CLI:
    python tests/make_hf_fixture.py /tmp/qwen2-micro
"""

from __future__ import annotations

import sys
from pathlib import Path

CHAT_TEMPLATE = (
    "{% for message in messages %}"
    "{{ '<|im_start|>' + message['role'] + '\n' + message['content'] }}"
    "{{ '<|im_end|>' + '\n' }}"
    "{% endfor %}"
    "{% if add_generation_prompt %}{{ '<|im_start|>assistant\n' }}{% endif %}"
)

SAMPLE_TEXT = [
    "The quick brown fox jumps over the lazy dog.",
    "Message queues decouple producers from consumers.",
    "Tensor processing units excel at dense linear algebra.",
    "Translate the following sentence into German.",
    "Continuous batching keeps the accelerator busy.",
    "Paged attention stores the KV cache in fixed-size blocks.",
] * 50


def build(out_dir: str | Path, *, vocab_size: int = 512) -> Path:
    import torch
    import transformers
    from tokenizers import Tokenizer, models, pre_tokenizers, trainers
    from transformers import PreTrainedTokenizerFast

    out = Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)

    tok = Tokenizer(models.BPE(unk_token=None))
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    trainer = trainers.BpeTrainer(
        vocab_size=vocab_size - 4,
        special_tokens=["<|endoftext|>", "<|im_start|>", "<|im_end|>", "<|pad|>"],
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet(),
    )
    tok.train_from_iterator(SAMPLE_TEXT, trainer=trainer)
    fast = PreTrainedTokenizerFast(
        tokenizer_object=tok,
        eos_token="<|im_end|>",
        pad_token="<|pad|>",
        bos_token=None,
        chat_template=CHAT_TEMPLATE,
    )
    fast.save_pretrained(out)

    true_vocab = fast.vocab_size
    torch.manual_seed(0)
    cfg = transformers.Qwen2Config(
        vocab_size=max(true_vocab, vocab_size),
        hidden_size=128,
        intermediate_size=256,
        num_hidden_layers=4,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=1024,
        rope_theta=10000.0,
        tie_word_embeddings=False,
        eos_token_id=fast.eos_token_id,
    )
    model = transformers.Qwen2ForCausalLM(cfg).eval().to(torch.float32)
    model.save_pretrained(out, safe_serialization=True, max_shard_size="500KB")
    return out


if __name__ == "__main__":
    dest = build(sys.argv[1] if len(sys.argv) > 1 else "/tmp/qwen2-micro")
    print(dest)
