"""Worker loop behavior: processing, passthrough, error policy, pipelines.

Pattern mirrors the reference's integration tests (real broker semantics via
the in-process broker + DummyWorker as fake backend, test_integration.py).
"""

import asyncio
import json

from llmq_tpu.broker.manager import BrokerManager
from llmq_tpu.core.config import Config
from llmq_tpu.core.models import Job, Result
from llmq_tpu.core.pipeline import PipelineConfig
from llmq_tpu.workers.dedup import DROPPED_MARKER, DedupWorker, embed, select_keep_mask
from llmq_tpu.workers.dummy import DummyWorker


async def _drain_results(mgr, queue, n, timeout=10.0):
    out = []
    deadline = asyncio.get_running_loop().time() + timeout
    while len(out) < n and asyncio.get_running_loop().time() < deadline:
        msg = await mgr.broker.get(queue)
        if msg is None:
            await asyncio.sleep(0.02)
            continue
        out.append(Result(**json.loads(msg.body)))
        await msg.ack()
    return out


async def _run_worker_until(worker, condition, timeout=10.0):
    task = asyncio.ensure_future(worker.run())
    deadline = asyncio.get_running_loop().time() + timeout
    while not condition() and asyncio.get_running_loop().time() < deadline:
        await asyncio.sleep(0.02)
    worker.request_shutdown()
    await asyncio.wait_for(task, timeout=15.0)


class TestDummyWorker:
    async def test_end_to_end(self, mem_url):
        cfg = Config(broker_url=mem_url)
        async with BrokerManager(cfg) as mgr:
            await mgr.setup_queue_infrastructure("q")
            for i in range(5):
                await mgr.publish_job(
                    "q", Job(id=f"j{i}", prompt="say {word}", word=f"w{i}")
                )
            worker = DummyWorker("q", delay=0, config=cfg, concurrency=4)
            await _run_worker_until(worker, lambda: worker.jobs_processed >= 5)
            results = await _drain_results(mgr, "q.results", 5)
            assert {r.id for r in results} == {f"j{i}" for i in range(5)}
            r0 = next(r for r in results if r.id == "j0")
            assert r0.result == "echo say w0"
            assert r0.prompt == "say w0"
            # extra-field passthrough
            assert json.loads(r0.model_dump_json())["word"] == "w0"

    async def test_malformed_job_dropped(self, mem_url):
        cfg = Config(broker_url=mem_url)
        async with BrokerManager(cfg) as mgr:
            await mgr.setup_queue_infrastructure("q")
            await mgr.broker.publish("q", b"this is not json")
            await mgr.publish_job("q", Job(id="ok", prompt="fine"))
            worker = DummyWorker("q", delay=0, config=cfg)
            await _run_worker_until(worker, lambda: worker.jobs_processed >= 1)
            assert worker.jobs_failed == 1
            stats = await mgr.get_queue_stats("q")
            assert stats.message_count == 0  # bad message not requeued

    async def test_unparseable_payload_dead_lettered_with_error(self, mem_url):
        """Corrupt payloads land in <q>.failed with an x-error header
        instead of silently vanishing."""
        cfg = Config(broker_url=mem_url)
        async with BrokerManager(cfg) as mgr:
            await mgr.setup_queue_infrastructure("q")
            await mgr.broker.publish("q", b"\x00garbage payload")
            worker = DummyWorker("q", delay=0, config=cfg)
            await _run_worker_until(worker, lambda: worker.jobs_failed >= 1)
            msg = await mgr.broker.get("q.failed")
            assert msg is not None
            assert msg.body == b"\x00garbage payload"
            assert "unparseable" in msg.headers.get("x-error", "")
            assert msg.headers.get("x-worker-id") == worker.worker_id
            assert msg.headers.get("x-death-queue") == "q"
            await msg.ack()
            # And the original is gone from the main queue.
            assert (await mgr.get_queue_stats("q")).message_count == 0

    async def test_job_timeout_requeues_then_dead_letters(self, mem_url):
        """A job sleeping past job_timeout_s is requeued; past the
        redelivery cap it dead-letters to <q>.failed."""

        class SleepyWorker(DummyWorker):
            async def _process_job(self, job):
                await asyncio.sleep(30)

        cfg = Config(broker_url=mem_url, job_timeout_s=0.1, max_redeliveries=1)
        async with BrokerManager(cfg) as mgr:
            await mgr.setup_queue_infrastructure("q")
            await mgr.publish_job("q", Job(id="hung", prompt="p"))
            worker = SleepyWorker("q", delay=0, config=cfg)
            # Initial delivery + 1 redelivery both time out, then DLQ.
            await _run_worker_until(worker, lambda: worker.jobs_timed_out >= 2)
            await asyncio.sleep(0.1)
            errors = await mgr.get_failed_jobs("q")
            assert len(errors) == 1
            assert errors[0].job_id == "hung"
            assert errors[0].redeliveries > 1
            assert worker.jobs_timed_out >= 2
            assert (await mgr.get_queue_stats("q")).message_count == 0

    async def test_no_timeout_when_unset(self, mem_url):
        """job_timeout_s=None (the default) imposes no deadline."""

        class MeasuredWorker(DummyWorker):
            async def _process_job(self, job):
                await asyncio.sleep(0.2)
                return "done"

        cfg = Config(broker_url=mem_url)
        assert cfg.job_timeout_s is None
        async with BrokerManager(cfg) as mgr:
            await mgr.setup_queue_infrastructure("q")
            await mgr.publish_job("q", Job(id="ok", prompt="p"))
            worker = MeasuredWorker("q", delay=0, config=cfg)
            await _run_worker_until(worker, lambda: worker.jobs_processed >= 1)
            assert worker.jobs_timed_out == 0
            results = await _drain_results(mgr, "q.results", 1)
            assert results[0].result == "done"

    async def test_processing_error_requeues_then_dlqs(self, mem_url):
        class FailingWorker(DummyWorker):
            async def _process_job(self, job):
                raise RuntimeError("boom")

        cfg = Config(broker_url=mem_url, max_redeliveries=1)
        async with BrokerManager(cfg) as mgr:
            await mgr.setup_queue_infrastructure("q")
            await mgr.publish_job("q", Job(id="doomed", prompt="p"))
            worker = FailingWorker("q", delay=0, config=cfg)
            # 1 initial + 1 redelivery then DLQ
            await _run_worker_until(worker, lambda: worker.jobs_failed >= 2)
            await asyncio.sleep(0.1)
            errors = await mgr.get_failed_jobs("q")
            assert len(errors) == 1
            assert errors[0].job_id == "doomed"

    async def test_invalid_job_value_error_acked(self, mem_url):
        class PickyWorker(DummyWorker):
            async def _process_job(self, job):
                raise ValueError("semantically bad")

        cfg = Config(broker_url=mem_url)
        async with BrokerManager(cfg) as mgr:
            await mgr.setup_queue_infrastructure("q")
            await mgr.publish_job("q", Job(id="bad", prompt="p"))
            worker = PickyWorker("q", delay=0, config=cfg)
            await _run_worker_until(worker, lambda: worker.jobs_failed >= 1)
            stats = await mgr.get_queue_stats("q")
            assert stats.message_count == 0  # dropped, not requeued

    async def test_chat_messages(self, mem_url):
        cfg = Config(broker_url=mem_url)
        async with BrokerManager(cfg) as mgr:
            await mgr.setup_queue_infrastructure("q")
            await mgr.publish_job(
                "q", Job(id="c", messages=[{"role": "user", "content": "hoi"}])
            )
            worker = DummyWorker("q", delay=0, config=cfg)
            await _run_worker_until(worker, lambda: worker.jobs_processed >= 1)
            results = await _drain_results(mgr, "q.results", 1)
            assert results[0].result == "echo hoi"


class TestPipelineWorkers:
    async def test_two_stage_pipeline(self, mem_url):
        pipeline = PipelineConfig.from_yaml_string(
            """
name: twostep
stages:
  - name: first
    worker: dummy
  - name: second
    worker: dummy
    config:
      prompt: "stage2 saw: {result}"
"""
        )
        cfg = Config(broker_url=mem_url)
        async with BrokerManager(cfg) as mgr:
            await mgr.setup_pipeline_infrastructure(pipeline)
            q1 = pipeline.get_stage_queue_name("first")
            await mgr.publish_job(q1, Job(id="x", prompt="start", source="test"))

            w1 = DummyWorker(
                q1, delay=0, config=cfg, pipeline=pipeline, stage_name="first"
            )
            w2 = DummyWorker(
                pipeline.get_stage_queue_name("second"),
                delay=0,
                config=cfg,
                pipeline=pipeline,
                stage_name="second",
            )
            t1 = asyncio.ensure_future(w1.run())
            t2 = asyncio.ensure_future(w2.run())
            final = await _drain_results(mgr, "pipeline.twostep.results", 1)
            w1.request_shutdown()
            w2.request_shutdown()
            await asyncio.gather(t1, t2)
            assert len(final) == 1
            # stage-2 template applied to stage-1 output (the fix)
            assert final[0].result == "echo stage2 saw: echo start"
            # passthrough extra survived both hops
            assert json.loads(final[0].model_dump_json())["source"] == "test"


class TestDedupMath:
    def test_embed_shapes_and_norm(self):
        import numpy as np

        v = embed(["hello world", "hello world!", "totally different text"])
        assert v.shape[0] == 3
        norms = np.linalg.norm(v, axis=1)
        assert np.allclose(norms, 1.0, atol=1e-5)
        sim_close = float(v[0] @ v[1])
        sim_far = float(v[0] @ v[2])
        assert sim_close > sim_far

    def test_dedup_mask(self):
        texts = ["the quick brown fox", "the quick brown fox!", "unrelated zebra"]
        keep = select_keep_mask(embed(texts), "dedup", threshold=0.8)
        assert keep.tolist() == [True, False, True]

    def test_representative_mask(self):
        texts = [
            "alpha beta gamma",
            "alpha beta gamma delta",
            "omega psi chi",
        ]
        keep = select_keep_mask(embed(texts), "representative", threshold=0.7)
        assert keep[0] and keep[2]

    def test_outliers_mask_keeps_fraction(self):
        texts = ["cat dog", "cat dog bird", "cat dog fish", "quantum entanglement"]
        keep = select_keep_mask(embed(texts), "outliers", threshold=0.75)
        assert keep.sum() == 3
        assert not keep[3]

    def test_embedding_stable_across_hash_seeds(self):
        """Two workers with different PYTHONHASHSEED must make IDENTICAL
        keep/drop decisions on a shared queue. Python's builtin hash()
        on str is salted per process, so an n-gram bucketing built on it
        silently degrades dedup to per-process agreement only; the
        blake2b bucketing must produce bit-identical vectors and masks
        regardless of the seed."""
        import subprocess
        import sys

        script = (
            "import json\n"
            "from llmq_tpu.workers.dedup import embed, select_keep_mask\n"
            "texts = ['the quick brown fox', 'the quick brown fox!',\n"
            "         'unrelated zebra', 'quantum entanglement']\n"
            "v = embed(texts)\n"
            "keep = select_keep_mask(v, 'dedup', threshold=0.8)\n"
            "print(json.dumps({'keep': keep.tolist(),\n"
            "                  'vec': v.round(6).tolist()}))\n"
        )
        outs = []
        for seed in ("0", "12345"):
            proc = subprocess.run(
                [sys.executable, "-c", script],
                capture_output=True, text=True, timeout=120,
                env={**__import__('os').environ, "PYTHONHASHSEED": seed,
                     "JAX_PLATFORMS": "cpu"},
            )
            assert proc.returncode == 0, proc.stderr[-2000:]
            outs.append(json.loads(proc.stdout))
        assert outs[0] == outs[1]
        assert outs[0]["keep"] == [True, False, True, True]


class TestSemanticDedup:
    """The model-embedding backend catches paraphrases the lexical
    n-gram mode cannot (reference semhash_worker.py:60-157 capability)."""

    # Paraphrase pair: same meaning, near-zero character-n-gram overlap.
    PARA_A = "the cat sat on the mat"
    PARA_B = "a feline rested upon a rug"
    UNRELATED = "quantum flux generator"

    @staticmethod
    def _embedder():
        import numpy as np

        from llmq_tpu.workers.dedup import ModelEmbedder

        # A tiny embedding table that encodes synonymy the way a trained
        # table does: synonym words share a vector. The test verifies the
        # *mechanism* (tokenize → mean-pool → cosine); a real checkpoint
        # supplies real synonymy through the identical code path.
        groups = [
            ("the", "a"),
            ("cat", "feline"),
            ("sat", "rested"),
            ("on", "upon"),
            ("mat", "rug"),
            ("quantum",),
            ("flux",),
            ("generator",),
        ]
        vocab = {}
        rows = []
        for gi, words in enumerate(groups):
            vec = np.zeros(len(groups), np.float32)
            vec[gi] = 1.0
            for w in words:
                vocab[w] = len(rows)
                rows.append(vec)
        table = np.stack(rows)
        tokenize = lambda t: [  # noqa: E731
            vocab[w] for w in t.lower().split() if w in vocab
        ]
        return ModelEmbedder(tokenize, table)

    def test_paraphrase_defeats_lexical_mode(self):
        texts = [self.PARA_A, self.PARA_B, self.UNRELATED]
        keep = select_keep_mask(embed(texts), "dedup", threshold=0.8)
        assert keep.tolist() == [True, True, True]  # lexical: all "unique"

    def test_model_embedding_catches_paraphrase(self):
        texts = [self.PARA_A, self.PARA_B, self.UNRELATED]
        vectors = self._embedder()(texts)
        sims = vectors @ vectors.T
        assert sims[0, 1] > 0.95  # paraphrases land together
        assert sims[0, 2] < 0.5  # unrelated text stays apart
        keep = select_keep_mask(vectors, "dedup", threshold=0.8)
        assert keep.tolist() == [True, False, True]

    async def test_semantic_worker_end_to_end(self, mem_url):
        cfg = Config(broker_url=mem_url)
        async with BrokerManager(cfg) as mgr:
            await mgr.setup_queue_infrastructure("sd")
            texts = [self.PARA_A, self.PARA_B, self.UNRELATED]
            for i, t in enumerate(texts):
                await mgr.publish_job("sd", Job(id=f"s{i}", prompt="{text}", text=t))
            worker = DedupWorker(
                "sd",
                batch_size=3,
                threshold=0.8,
                embedder=self._embedder(),
                config=cfg,
                concurrency=8,
            )
            await _run_worker_until(worker, lambda: worker.jobs_processed >= 3)
            results = await _drain_results(mgr, "sd.results", 3)
            by_id = {r.id: r.result for r in results}
            assert by_id["s0"] == self.PARA_A
            assert by_id["s1"] == DROPPED_MARKER  # caught only semantically
            assert by_id["s2"] == self.UNRELATED

    def test_from_checkpoint_loads_embedding_table(self, tmp_path):
        """The --embedding model loading path against a genuine offline
        HF checkpoint (sharded safetensors + tokenizer.json)."""
        import pytest

        pytest.importorskip("torch")  # fixture builds with torch
        pytest.importorskip("transformers")
        pytest.importorskip("tokenizers")
        from tests.make_hf_fixture import build

        from llmq_tpu.workers.dedup import ModelEmbedder

        import numpy as np

        path = build(tmp_path / "hf-micro")
        emb = ModelEmbedder.from_checkpoint(str(path))
        v = emb(["hello world", "hello world", "completely different"])
        assert v.shape[0] == 3
        assert np.allclose(np.linalg.norm(v, axis=1), 1.0, atol=1e-5)
        assert float(v[0] @ v[1]) > 0.999  # identical text, identical vector


class TestDedupWorker:
    async def test_batch_dedup_end_to_end(self, mem_url):
        cfg = Config(broker_url=mem_url)
        async with BrokerManager(cfg) as mgr:
            await mgr.setup_queue_infrastructure("d")
            texts = ["same text here", "same text here", "different content"]
            for i, t in enumerate(texts):
                await mgr.publish_job("d", Job(id=f"t{i}", prompt="{text}", text=t))
            worker = DedupWorker(
                "d", batch_size=3, threshold=0.95, config=cfg, concurrency=8
            )
            await _run_worker_until(worker, lambda: worker.jobs_processed >= 3)
            results = await _drain_results(mgr, "d.results", 3)
            by_id = {r.id: r.result for r in results}
            assert by_id["t0"] == "same text here"
            assert by_id["t1"] == DROPPED_MARKER
            assert by_id["t2"] == "different content"

    async def test_partial_batch_flushes_on_shutdown(self, mem_url):
        cfg = Config(broker_url=mem_url)
        async with BrokerManager(cfg) as mgr:
            await mgr.setup_queue_infrastructure("d")
            await mgr.publish_job("d", Job(id="only", prompt="{text}", text="solo"))
            worker = DedupWorker("d", batch_size=100, config=cfg)
            worker.idle_flush_s = 0.2  # fast idle flush for the test
            await _run_worker_until(worker, lambda: worker.jobs_processed >= 1)
            results = await _drain_results(mgr, "d.results", 1)
            assert results[0].result == "solo"
