"""Prefix-affinity routing + cross-worker page shipping.

Layer 2/3 of the fleet-wide prefix cache: workers advertise hot
text-chain digests in heartbeats; ``BrokerManager.publish_job`` routes
jobs sharing an advertised prefix to the advertiser's private queue
``<q>.w.<worker_id>``; a worker that gets a job whose prefix pages live
on a peer fetches them over ``<q>.kv.<worker_id>`` instead of
recomputing. Everything is best-effort: no fresh heartbeat, no peer, or
a fetch timeout all degrade to the shared queue / a plain prefill.
"""

import asyncio
import json

import pytest

from llmq_tpu.broker.manager import (
    HEALTH_SUFFIX,
    BrokerManager,
    affinity_queue_name,
    job_affinity_text,
    kv_fetch_queue_name,
    rendezvous_pick,
)
from llmq_tpu.core.config import Config, get_config
from llmq_tpu.core.models import Job, WorkerHealth, utcnow
from llmq_tpu.utils.hashing import text_prefix_chain
from llmq_tpu.workers.tpu_worker import TPUWorker

# ≥256 chars so text_prefix_chain yields at least one digest; templated
# jobs share it, unrelated jobs don't.
TEMPLATE = ("SYSTEM: you are a helpful assistant. " * 8)[:280]


def make_config(mem_url, **kw):
    kw.setdefault("prefix_affinity", True)
    return Config(broker_url=mem_url, **kw)


def make_worker(mem_url, queue="aff-q", **kw):
    kw.setdefault("model", "preset://tiny")
    kw.setdefault("tensor_parallel", 1)
    kw.setdefault("max_model_len", 512)
    kw.setdefault("num_pages", 80)
    kw.setdefault("page_size", 8)
    kw.setdefault("dtype", "float32")
    kw.setdefault("max_num_seqs", 4)
    kw.setdefault("prefill_chunk_size", 8)
    kw.setdefault("enable_prefix_caching", True)
    config = kw.pop("config", None) or make_config(mem_url)
    return TPUWorker(queue, config=config, concurrency=4, **kw)


# --- pure helpers -----------------------------------------------------------


class TestHelpers:
    def test_config_env_flag(self, monkeypatch):
        monkeypatch.setenv("LLMQ_PREFIX_AFFINITY", "1")
        assert get_config().prefix_affinity is True
        monkeypatch.setenv("LLMQ_PREFIX_AFFINITY", "0")
        assert get_config().prefix_affinity is False
        monkeypatch.delenv("LLMQ_PREFIX_AFFINITY")
        assert get_config().prefix_affinity is False

    def test_worker_health_prefix_chains_roundtrip(self):
        chains = text_prefix_chain(TEMPLATE + "tail")
        health = WorkerHealth(
            worker_id="w1",
            status="running",
            last_seen=utcnow(),
            jobs_processed=3,
            prefix_chains=chains,
        )
        again = WorkerHealth.model_validate_json(health.model_dump_json())
        assert again.prefix_chains == chains
        # Pre-affinity heartbeats (no field) still parse.
        old = json.loads(health.model_dump_json())
        del old["prefix_chains"]
        assert WorkerHealth.model_validate(old).prefix_chains is None

    def test_rendezvous_deterministic_and_stable(self):
        workers = ["w1", "w2", "w3"]
        winner = rendezvous_pick("ab" * 16, workers)
        assert winner in workers
        assert winner == rendezvous_pick("ab" * 16, list(reversed(workers)))
        # Removing a losing advertiser never remaps the chain.
        rest = [w for w in workers if w != winner]
        loser_gone = [w for w in workers if w != rest[0]]
        assert rendezvous_pick("ab" * 16, loser_gone) == winner

    def test_job_affinity_text(self):
        job = Job(id="j", prompt="say {word}", word="hello")
        assert job_affinity_text(job) == "say hello"
        chat = Job(id="c", messages=[{"role": "user", "content": "hi"}])
        assert job_affinity_text(chat) == "hi"
        # Unresolved placeholders pass through verbatim (the worker
        # formats identically, so digests still agree) — never raise.
        broken = Job(id="b", prompt="say {missing}")
        assert job_affinity_text(broken) == "say {missing}"

    def test_queue_names(self):
        assert affinity_queue_name("q", "w1") == "q.w.w1"
        assert kv_fetch_queue_name("q", "w1") == "q.kv.w1"


# --- routing over the memory broker -----------------------------------------


async def _mgr_with_advert(mem_url, queue, worker_id, chains, *, age_s=0.0):
    """A connected manager plus one advertised heartbeat on the health
    queue (and the advertiser's private queue declared, as the worker
    itself would have done)."""
    mgr = BrokerManager(make_config(mem_url))
    await mgr.connect()
    await mgr.setup_queue_infrastructure(queue)
    await mgr.broker.declare_queue(
        queue + HEALTH_SUFFIX, ttl_ms=120_000, max_redeliveries=1_000_000_000
    )
    await mgr.broker.declare_queue(affinity_queue_name(queue, worker_id))
    last_seen = utcnow()
    if age_s:
        from datetime import timedelta

        last_seen = last_seen - timedelta(seconds=age_s)
    health = WorkerHealth(
        worker_id=worker_id,
        status="running",
        last_seen=last_seen,
        jobs_processed=1,
        prefix_chains=chains,
    )
    await mgr.broker.publish(
        queue + HEALTH_SUFFIX, health.model_dump_json().encode("utf-8")
    )
    return mgr


async def test_routes_to_advertising_worker(mem_url):
    chains = text_prefix_chain(TEMPLATE + "anything")
    mgr = await _mgr_with_advert(mem_url, "q", "w1", chains)
    try:
        await mgr.publish_job("q", Job(id="j1", prompt=TEMPLATE + " Q?"))
        msg = await mgr.broker.get(affinity_queue_name("q", "w1"))
        assert msg is not None, "templated job should land on w1's queue"
        assert json.loads(msg.body)["id"] == "j1"
        await msg.ack()
        assert await mgr.broker.get("q") is None
        assert mgr.affinity_routed == 1 and mgr.affinity_fallback == 0
    finally:
        await mgr.disconnect()


async def test_unrelated_job_falls_back_to_shared_queue(mem_url):
    chains = text_prefix_chain(TEMPLATE + "anything")
    mgr = await _mgr_with_advert(mem_url, "q", "w1", chains)
    try:
        await mgr.publish_job("q", Job(id="j2", prompt="X" * 300))
        msg = await mgr.broker.get("q")
        assert msg is not None, "unrelated job belongs on the shared queue"
        await msg.ack()
        assert await mgr.broker.get(affinity_queue_name("q", "w1")) is None
        assert mgr.affinity_fallback == 1
    finally:
        await mgr.disconnect()


async def test_short_prompt_never_routes(mem_url):
    """Prompts under one text chunk have no chain — always shared."""
    chains = text_prefix_chain(TEMPLATE + "anything")
    mgr = await _mgr_with_advert(mem_url, "q", "w1", chains)
    try:
        await mgr.publish_job("q", Job(id="j3", prompt="short"))
        assert (msg := await mgr.broker.get("q")) is not None
        await msg.ack()
    finally:
        await mgr.disconnect()


async def test_stale_heartbeat_does_not_route(mem_url):
    """An advertisement older than the freshness window is dead weight:
    the worker (and its pages) may be gone, so jobs stay shared."""
    chains = text_prefix_chain(TEMPLATE + "anything")
    mgr = await _mgr_with_advert(mem_url, "q", "w1", chains, age_s=600.0)
    try:
        await mgr.publish_job("q", Job(id="j4", prompt=TEMPLATE + " Q?"))
        assert (msg := await mgr.broker.get("q")) is not None
        await msg.ack()
        assert await mgr.broker.get(affinity_queue_name("q", "w1")) is None
    finally:
        await mgr.disconnect()


async def test_affinity_off_never_peeks(mem_url):
    """With the flag off, publish_job must not touch the health queue
    (routing work is pure overhead for non-templated fleets)."""
    mgr = BrokerManager(make_config(mem_url, prefix_affinity=False))
    await mgr.connect()
    try:
        await mgr.setup_queue_infrastructure("q")
        await mgr.publish_job("q", Job(id="j5", prompt=TEMPLATE + " Q?"))
        assert (msg := await mgr.broker.get("q")) is not None
        await msg.ack()
        assert mgr.affinity_routed == 0 and mgr.affinity_fallback == 0
    finally:
        await mgr.disconnect()


async def test_affinity_map_caches_between_publishes(mem_url):
    """The heartbeat peek happens at most once per refresh window, not
    once per job — submit loops run at full rate."""
    chains = text_prefix_chain(TEMPLATE + "anything")
    mgr = await _mgr_with_advert(mem_url, "q", "w1", chains)
    try:
        for i in range(5):
            await mgr.publish_job("q", Job(id=f"b-{i}", prompt=TEMPLATE + "?"))
        assert mgr.affinity_routed == 5
        # The single peeked-and-requeued heartbeat is still there.
        beats = await mgr.get_worker_health("q")
        assert set(beats) == {"w1"}
    finally:
        await mgr.disconnect()


# --- cross-worker page shipping (two real engines) --------------------------


@pytest.mark.integration
async def test_two_workers_ship_prefix_pages(mem_url, monkeypatch):
    """The full layer-3 path: worker A builds prefix pages from
    templated traffic and advertises them; worker B, handed a job with
    the same template, fetches the missing KV pages from A over the
    broker and lands them in its host tier instead of recomputing."""
    monkeypatch.setenv("LLMQ_PREFIX_HOST_GB", "0.05")
    queue = "ship-q"
    jobs = [
        Job(
            id=f"warm-{i}",
            prompt=TEMPLATE + f" item {i}",
            temperature=0.0,
            max_tokens=4,
            ignore_eos=True,
        )
        for i in range(2)
    ]
    worker_a = make_worker(mem_url, queue=queue)
    broker = BrokerManager(make_config(mem_url))
    await broker.connect()
    await broker.setup_queue_infrastructure(queue)
    task_a = asyncio.create_task(worker_a.run())
    worker_b = None
    try:
        # Wait for A's consumers (incl. the kv-fetch server) to attach.
        deadline = asyncio.get_event_loop().time() + 120.0
        while worker_a._kv_consumer_tag is None:
            assert asyncio.get_event_loop().time() < deadline, "A never ready"
            await asyncio.sleep(0.05)
        results = []

        async def handler(message):
            results.append(message)
            await message.ack()

        await broker.consume_results(queue + ".results", handler)
        for job in jobs:
            await broker.publish_job(queue, job)
        while len(results) < len(jobs):
            assert asyncio.get_event_loop().time() < deadline
            await asyncio.sleep(0.05)
        # A processed templated traffic: it tracked the text chain and
        # holds the prefix pages (device cache and/or host tier).
        assert worker_a._prefix_chains(), "A should advertise chains"
        await worker_a._publish_heartbeat()

        # B: fresh engine, empty caches, same fleet config. Same process
        # as A, so disambiguate the host-pid-derived worker id BEFORE the
        # queues keyed on it are declared.
        worker_b = make_worker(mem_url, queue=queue)
        worker_b.worker_id = worker_b.worker_id + "-b"
        await worker_b.initialize()
        await worker_b._start_extra_consumers()
        job = Job(
            id="cold-on-b",
            prompt=TEMPLATE + " item 99",
            temperature=0.0,
            max_tokens=4,
            ignore_eos=True,
        )
        store_b = worker_b.engine.core.prefix_store
        assert store_b is not None and len(store_b) == 0
        await worker_b._maybe_fetch_prefix(job, job_affinity_text(job))
        assert worker_b.prefix_chunks_fetched > 0, "B fetched nothing"
        assert len(store_b) == worker_b.prefix_chunks_fetched
        assert worker_a.prefix_chunks_served >= worker_b.prefix_chunks_fetched
        # The shipped pages are the REAL thing: processing the job on B
        # promotes them (prefix hits) instead of re-prefilling.
        hits_before = worker_b.engine.core.scheduler.prefix_hits
        out = await worker_b._process_job(job)
        assert isinstance(out, str)
        assert worker_b.engine.core.scheduler.prefix_hits > hits_before
        assert worker_b.engine.core.prefix_promotes > 0
    finally:
        if worker_b is not None:
            await worker_b.shutdown()
        worker_a.request_shutdown()
        await asyncio.wait_for(task_a, timeout=60)
        await broker.disconnect()


@pytest.mark.integration
async def test_fetch_timeout_degrades_to_recompute(mem_url, monkeypatch):
    """A dead peer (advertised but not serving) must cost ~the fetch
    timeout, not correctness: the job still processes locally."""
    monkeypatch.setenv("LLMQ_PREFIX_HOST_GB", "0.05")
    import llmq_tpu.workers.tpu_worker as tw

    monkeypatch.setattr(tw, "PREFIX_FETCH_TIMEOUT_S", 0.3)
    queue = "dead-peer-q"
    chains = text_prefix_chain(TEMPLATE + "anything")
    mgr = await _mgr_with_advert(mem_url, queue, "ghost", chains)
    worker = None
    try:
        # The ghost's kv queue exists (it "ran once") but nothing consumes.
        await mgr.broker.declare_queue(
            kv_fetch_queue_name(queue, "ghost"), ttl_ms=30_000
        )
        worker = make_worker(mem_url, queue=queue)
        await worker.initialize()
        job = Job(
            id="orphan",
            prompt=TEMPLATE + " item",
            temperature=0.0,
            max_tokens=3,
            ignore_eos=True,
        )
        await worker._maybe_fetch_prefix(job, job_affinity_text(job))
        assert worker.prefix_fetch_timeouts == 1
        assert worker.prefix_chunks_fetched == 0
        out = await worker._process_job(job)
        assert isinstance(out, str) and len(out) > 0
    finally:
        if worker is not None:
            await worker.shutdown()
        await mgr.disconnect()
