#!/usr/bin/env python
"""Headline benchmark: engine decode throughput on the local chip(s).

Prints ONE JSON line (always — even when the accelerator backend fails):
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "mfu": N}

What it measures: output tokens/sec of the continuous-batching engine on
the largest architecture preset that fits device HBM, random weights
(numerics identical to a real checkpoint), synthetic token prompts —
the TPU-native counterpart of the reference's `performance_benchmark.py`
"output tokens/sec" metric (reference performance_benchmark.py:329-335).

Baseline: the reference publishes no absolute numbers (BASELINE.md). The
north star is "Tower-Plus-9B at >= A100-class tokens/sec/chip"
(BASELINE.json). We take 1500 output tok/s as the A100-class figure for a
9B dense decoder under vLLM continuous batching and scale it inversely
with parameter count for smaller benched models:
    baseline(model) = 1500 * 9e9 / n_params.
``vs_baseline`` > 1.0 means faster than that A100-class estimate.

``mfu`` = achieved model FLOPs / chip peak bf16 FLOPs, with model FLOPs
approximated as 2 * n_params per generated token (matmul-dominated decode).

Robustness (the round-1 bench died on a transient TPU-tunnel init error
before printing anything): backend init is retried with backoff, falls
back to CPU, and any late failure still emits the JSON line with an
``error`` field.

Env knobs: LLMQ_BENCH_PRESET, LLMQ_BENCH_REQUESTS, LLMQ_BENCH_PROMPT,
LLMQ_BENCH_GEN, LLMQ_BENCH_SEQS, LLMQ_BENCH_KV_DTYPE (fp8 = e5m2 KV
cache), LLMQ_BENCH_INIT_RETRIES (default 2),
LLMQ_BENCH_INIT_TIMEOUT (seconds per backend probe, default 120),
LLMQ_BENCH_DEADLINE (whole-run watchdog seconds, default 3600 —
sized for the quantized attempt plus the slot ladder running the
headline at both candidates),
LLMQ_BENCH_TRY_QUANT=0 (skip the int8+fp8 subprocess attempt that
otherwise runs first on accelerators and wins the emit when it clearly
beats baseline), LLMQ_BENCH_QUANT_TIMEOUT (its budget, default 1500 s — the int8
ladder tries up to three slot counts), LLMQ_BENCH_DECODE_BLOCK (pin the
fused decode-block size K; unset -> the ladder measures K=2/4 at the
winning slot count after the slot ladder and emits the best),
LLMQ_BENCH_SPEC_TOKENS (pin the speculative-decoding draft length;
unset -> the spec rung measures prompt-lookup drafting at the winning
(slots, K) point after the decode-block ladder and keeps it only if it
wins), LLMQ_BENCH_DTYPE=int4 (AWQ-style group-quantized layer weights;
also tried as a subprocess attempt on generous deadlines,
LLMQ_BENCH_TRY_INT4=0 to opt out), LLMQ_BENCH_PREFILL_CHUNK (chunk size
the mixed-step rung uses; the rung fuses prefill chunks into decode
dispatches at the winning point and keeps the mode only on a measured
win — pin engine-wide with LLMQ_MIXED_STEP instead).

When the remaining LLMQ_BENCH_DEADLINE budget cannot fit the whole plan
(quant attempt + kernel A/B + the multi-candidate ladder), phases are
trimmed in speculation order — see trim_plan() — down to, at minimum,
one bf16 headline at the proven 192-slot config.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Optional


def _emit(payload: dict) -> None:
    sys.stdout.write(json.dumps(payload) + "\n")
    sys.stdout.flush()


def _emit_failure(tag: str, error: str) -> None:
    if _QUANT_FALLBACK is not None:
        # The quantized attempt already produced a real measurement —
        # a later bf16 failure must not discard it for a 0.0 line.
        _emit({**_QUANT_FALLBACK, "note": f"bf16 run failed: {error}"})
        return
    _emit(
        {
            "metric": f"decode_tokens_per_sec_per_chip[{tag}]",
            "value": 0.0,
            "unit": "tok/s/chip",
            "vs_baseline": 0.0,
            "mfu": 0.0,
            "error": error,
        }
    )


def _last_hardware_metric_line() -> Optional[dict]:
    """The most recent hardware-measured metric line under PERF_RESULTS/.

    When the accelerator probe hangs and the bench falls back to host
    CPU, the tiny-preset number it would measure is meaningless as a
    deployment metric (the r05 driver recorded a ``vs_baseline: 0.0``
    line from exactly this path). The runbook logs under PERF_RESULTS/
    hold the last line measured on real hardware; re-emitting it,
    clearly annotated, keeps the artifact truthful about the
    deployment's known throughput instead of reporting a number no chip
    ever produced. Newest log file wins; within a file, the last valid
    line (value > 0, no error field) wins.
    """
    import glob

    best = None  # (mtime, payload)
    for path in sorted(glob.glob(os.path.join("PERF_RESULTS", "*.log"))):
        try:
            mtime = os.path.getmtime(path)
            if best is not None and mtime < best[0]:
                continue
            with open(path, encoding="utf-8", errors="replace") as fh:
                for line in fh:
                    line = line.strip()
                    if not (line.startswith("{") and '"metric"' in line):
                        continue
                    try:
                        payload = json.loads(line)
                    except ValueError:
                        continue
                    if not isinstance(payload, dict) or payload.get("error"):
                        continue
                    try:
                        if float(payload.get("value") or 0.0) <= 0.0:
                            continue
                    except (TypeError, ValueError):
                        continue
                    best = (mtime, payload)
        except OSError:
            continue
    return best[1] if best else None


def _arm_emit_watchdog(deadline_s: float, why: str):
    """Daemon timer: if not cancelled within ``deadline_s``, emit the
    failure JSON line and hard-exit. A hung PJRT call blocks in C and
    ignores signals, so printing-then-``os._exit`` is the only way to
    guarantee the artifact exists. Returns a cancel() callable."""
    import threading

    def fire():
        _emit_failure("hung", why)
        os._exit(3)

    timer = threading.Timer(deadline_s, fire)
    timer.daemon = True
    timer.start()
    return timer.cancel


_LIBTPU_LOCKFILE = "/tmp/libtpu_lockfile"


def _clear_stale_libtpu_lock() -> bool:
    """Remove a leftover libtpu lockfile if no live process holds it.

    libtpu serialises chip ownership through an advisory lockfile; a
    probe child killed at the deadline (or an OOM-killed worker) can
    leave it behind, and every subsequent probe then blocks waiting for
    a lock nobody holds — the r04 failure mode where one hung probe
    turned into a permanent CPU fallback. ``flock(LOCK_NB)`` succeeding
    proves no live process owns it, so deleting is safe.
    """
    path = os.environ.get("LLMQ_LIBTPU_LOCKFILE", _LIBTPU_LOCKFILE)
    if not os.path.exists(path):
        return False
    try:
        import fcntl

        with open(path, "a") as fh:
            try:
                fcntl.flock(fh, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                return False  # genuinely held by a live process
            fcntl.flock(fh, fcntl.LOCK_UN)
        os.unlink(path)
        print(
            f"bench: removed stale libtpu lockfile {path}", file=sys.stderr
        )
        return True
    except OSError:
        return False


# The child logs a marker before/after each step that can hang, so the
# parent can report WHERE the probe wedged instead of a bare timeout.
_PROBE_CHILD_SRC = (
    "import sys\n"
    "def mark(m):\n"
    "    print('probe-phase:' + m, file=sys.stderr, flush=True)\n"
    "mark('import-start')\n"
    "import jax\n"
    "mark('import-done')\n"
    "mark('devices-start')\n"
    "d = jax.devices()\n"
    "mark('devices-done')\n"
    "print(len(d), d[0].platform, flush=True)\n"
)


def _probe_backend_subprocess(timeout_s: float) -> bool:
    """Init the accelerator backend in a *child* process with a deadline.

    A TPU tunnel can *hang* inside ``jax.devices()`` (observed >240 s), not
    just raise — an in-process call would wedge the benchmark past the
    driver's timeout with no JSON emitted. The child either confirms the
    backend comes up (warming the server side) or is killed at the
    deadline.

    The child runs in its own *session* so the deadline kill reaches the
    whole process group — ``Popen.kill()`` alone leaves libtpu helper
    processes alive holding the chip lock, which is what wedged every
    retry (and the next bench run) after the first r04 hang. Teardown is
    staged SIGTERM→SIGKILL, the child's last progress marker is logged
    as the hang cause, and a stale lockfile is cleared before/after so
    the next attempt starts clean.
    """
    import signal
    import subprocess

    _clear_stale_libtpu_lock()
    proc = subprocess.Popen(
        [sys.executable, "-c", _PROBE_CHILD_SRC],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        start_new_session=True,
    )
    try:
        out, err = proc.communicate(timeout=timeout_s)
        ok = proc.returncode == 0
        if not ok:
            print(
                f"bench: backend probe rc={proc.returncode}: "
                f"{(err or '')[-400:]}",
                file=sys.stderr,
            )
        return ok
    except subprocess.TimeoutExpired:
        for sig, grace in ((signal.SIGTERM, 5.0), (signal.SIGKILL, 5.0)):
            try:
                os.killpg(proc.pid, sig)
            except (ProcessLookupError, PermissionError):
                break
            try:
                proc.wait(timeout=grace)
                break
            except subprocess.TimeoutExpired:
                continue
        err = ""
        try:
            _, err = proc.communicate(timeout=5.0)
        except Exception:  # noqa: BLE001 — pipes may be wedged too
            pass
        phases = [
            line.split(":", 1)[1]
            for line in (err or "").splitlines()
            if line.startswith("probe-phase:")
        ]
        where = phases[-1] if phases else "spawn"
        print(
            f"bench: backend probe hung past {timeout_s:.0f}s "
            f"(last phase: {where}) — falling back to cpu",
            file=sys.stderr,
        )
        if where in ("import-done", "devices-start"):
            # Hung inside device init: usually a dead tunnel or a lock
            # left by a previous kill; clear it so the retry differs.
            _clear_stale_libtpu_lock()
        return False


def init_devices():
    """jax.devices() with watchdog + retry + CPU fallback; never raises.

    The TPU plugin behind a tunnel can flake with UNAVAILABLE on first
    contact (BENCH_r01.json tail) or hang outright. Each attempt is
    probed in a subprocess under a deadline; only a confirmed-healthy
    backend is initialised in-process. If the accelerator never comes up
    we force the CPU platform so the benchmark still produces a
    (clearly-labelled) number instead of nothing.
    """
    import jax

    # Asked for host CPU (tests, CI): nothing can hang, no probe. The env
    # var must win even when this image's sitecustomize pinned the config
    # to "axon,cpu" (config outranks env, tests/conftest.py has the same
    # workaround).
    if (
        os.environ.get("JAX_PLATFORMS", "") == "cpu"
        or jax.config.jax_platforms == "cpu"
    ):
        from llmq_tpu.utils.platform import force_cpu_platform

        try:
            force_cpu_platform()
            return jax, jax.devices(), None
        except Exception as exc:  # noqa: BLE001
            return None, [], f"cpu backend failed: {exc}"

    retries = max(1, int(os.environ.get("LLMQ_BENCH_INIT_RETRIES", 2)))
    probe_timeout = float(os.environ.get("LLMQ_BENCH_INIT_TIMEOUT", 120))
    last_err = None
    for attempt in range(retries):
        if _probe_backend_subprocess(probe_timeout):
            # The probe's success doesn't bound the in-process init (the
            # tunnel could degrade in between, and a hung C call can't be
            # interrupted) — arm a last-resort watchdog that emits the
            # JSON artifact and exits rather than wedge past the driver's
            # deadline with nothing printed.
            cancel = _arm_emit_watchdog(
                probe_timeout + 60.0,
                "backend init hung in-process after a healthy probe",
            )
            try:
                devices = jax.devices()
                return jax, devices, None
            except Exception as exc:  # noqa: BLE001 — races are possible
                last_err = exc
            finally:
                cancel()
        else:
            last_err = "probe failed or hung"
        if attempt + 1 < retries:
            time.sleep(min(2.0 * 2**attempt, 10.0))
    # Accelerator unusable: fall back to host CPU.
    from llmq_tpu.utils.platform import force_cpu_platform

    try:
        force_cpu_platform()
        devices = jax.devices()
        return jax, devices, f"fell back to cpu: {last_err}"
    except Exception as exc:  # noqa: BLE001
        return None, [], f"no backend at all: {exc}"


def _backend_stamp(platform, backend_note):
    """Structured backend-probe outcome for the metric payload: which
    platform actually produced this line and, when the accelerator never
    came up, the probe's reason. Machine-readable on purpose — a driver
    partitioning BENCH_r*.json lines into hardware vs CPU-fallback must
    not have to parse the free-text ``note``."""
    fallback = bool(backend_note) and (
        backend_note.startswith("fell back to cpu")
        or backend_note.startswith("no backend")
    )
    stamp = {"platform": platform, "fallback": fallback}
    if backend_note:
        stamp["probe_note"] = backend_note
    return stamp


def pick_preset(
    limit_bytes, platform: str, *, int8: bool = False, int4: bool = False
) -> str:
    if platform == "cpu":
        return "tiny"
    gb = (limit_bytes or 16 * 2**30) / 2**30
    # bf16 params ~2 bytes each; leave room for KV cache + activations.
    # int8 weight-only quantization halves the parameter bytes — which is
    # what fits tower-plus-9b (north-star architecture) on a 16 GB chip.
    # int4 group quantization quarters the layer bytes (embed/lm_head
    # stay int8, scales+zeros add back a sliver).
    for preset, param_gb in (
        ("tower-plus-9b", 20.5),
        ("qwen2.5-7b", 15.2),
        ("qwen2.5-3b", 6.8),
        ("qwen2.5-1.5b", 3.6),
        ("qwen2.5-0.5b", 1.4),
    ):
        if int4:
            param_gb = param_gb / 4 + 0.4  # int4 bodies + scales/zeros
        elif int8:
            param_gb = param_gb / 2 + 0.3  # int8 bodies + scales/norms
        if gb * 0.92 > param_gb * 1.35:
            return preset
    return "qwen2.5-0.5b"


# Peak dense bf16 TFLOP/s per *jax device* by device-kind substring
# (public chip specs; v2/v3 expose one device per core = half a chip, so
# their entries are per-core). Used only for the MFU estimate.
_PEAK_TFLOPS = (
    ("v6", 918.0),  # Trillium
    ("v5p", 459.0),
    ("v5e", 197.0),  # v5 litepod
    ("v5", 197.0),
    ("v4", 275.0),
    ("v3", 61.5),  # per core (123 per chip)
    ("v2", 22.5),  # per core (45 per chip)
)


def peak_flops_per_chip(devices) -> float:
    kind = ""
    try:
        kind = (devices[0].device_kind or "").lower()
    except Exception:  # noqa: BLE001
        pass
    for key, tflops in _PEAK_TFLOPS:
        if key in kind:
            return tflops * 1e12
    if devices and getattr(devices[0], "platform", "") == "tpu":
        return 197.0e12  # unknown TPU: assume v5e-class
    return 100e9  # CPU-ish placeholder so mfu stays finite


def pick_decode_kernel() -> str:
    """Quick on-hardware A/B of the paged-decode kernels (v1 BlockSpec
    pipeline vs v2 chunked manual-DMA), run in a SUBPROCESS under a
    deadline. Two reasons for the subprocess: a kernel hang on a flaky
    tunnel must cost at most the A/B budget, never the headline run, and
    on standard TPU VMs libtpu is EXCLUSIVE — the probe must run (and
    exit) before this process initialises the backend. The child derives
    its own preset/shape from the same env knobs main() uses. An explicit
    LLMQ_DECODE_KERNEL always wins; any failure/timeout → v1.
    """
    import subprocess

    explicit = os.environ.get("LLMQ_DECODE_KERNEL")
    if explicit:
        return explicit
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--kernel-ab-probe"],
            timeout=float(os.environ.get("LLMQ_BENCH_AB_TIMEOUT", 420)),
            capture_output=True,
            text=True,
        )
        sys.stderr.write(proc.stderr[-600:])
        choice = proc.stdout.strip().splitlines()[-1] if proc.stdout else ""
        if proc.returncode == 0 and choice in ("v1", "v2", "v3"):
            return choice
        print(f"bench: kernel A/B rc={proc.returncode}; using v1", file=sys.stderr)
    except subprocess.TimeoutExpired:
        print("bench: kernel A/B timed out; using v1", file=sys.stderr)
    except Exception as exc:  # noqa: BLE001
        print(f"bench: kernel A/B failed ({exc!r}); using v1", file=sys.stderr)
    return "v1"


def _kernel_ab_probe_main() -> None:
    """Entry for `bench.py --kernel-ab-probe` (child process). Derives
    the preset the same way main() will (same env knobs, same HBM), so
    the A/B measures the shapes the headline run uses."""
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # Testability off-TPU: the axon sitecustomize pins the platform at
        # the CONFIG level, so the env var alone would still try (and hang
        # on) the tunnel.
        from llmq_tpu.utils.platform import force_cpu_platform

        force_cpu_platform()
    import jax

    from llmq_tpu.engine.kernel_autotune import run_ab
    from llmq_tpu.models.presets import get_preset

    devices = jax.devices()
    try:
        limit = (devices[0].memory_stats() or {}).get("bytes_limit")
    except Exception:  # noqa: BLE001
        limit = None
    preset = os.environ.get("LLMQ_BENCH_PRESET") or pick_preset(
        limit, devices[0].platform
    )
    config = get_preset(preset)
    kv_env = (os.environ.get("LLMQ_BENCH_KV_DTYPE") or "").lower()
    choice, _measured = run_ab(
        num_heads=config.num_heads,
        num_kv_heads=config.num_kv_heads,
        head_dim=config.head_dim_,
        num_layers=config.num_layers,
        max_seqs=int(os.environ.get("LLMQ_BENCH_SEQS", 192)),
        page_size=128,
        # The A/B must rank kernels at the production pool dtype (fp8
        # pools move half the bytes of bf16).
        kv_dtype="float8_e5m2" if kv_env in ("fp8", "fp8_e5m2",
                                             "float8_e5m2") else "bfloat16",
    )
    print(choice)


# Set when the quantized attempt produced a valid-but-not-clearly-winning
# number: the bf16 ladder runs too, and the better line is emitted. A
# module global (not a main() local) on purpose: the failure emitters —
# including the watchdog thread — must prefer this real measurement over
# a 0.0 failure line if the later bf16 run dies.
_QUANT_FALLBACK: Optional[dict] = None

# Wall-clock deadline (time.monotonic()) set in __main__ when the emit
# watchdog is armed; trim_plan() reads the remaining budget through
# _remaining_budget() to decide which phases still fit.
_DEADLINE_AT: Optional[float] = None

# The proven operating point: bf16, 192 slots (r05 ladder winner —
# 224 fit but measured ~3% slower). When the deadline can't fit the
# speculative phases, the bench skips straight here.
_PROVEN_BF16_SEQS = 192


def _remaining_budget() -> Optional[float]:
    """Seconds left before the emit watchdog fires (None = no deadline)."""
    if _DEADLINE_AT is None:
        return None
    return _DEADLINE_AT - time.monotonic()


def trim_plan(
    remaining_s: Optional[float],
    *,
    quant_s: float,
    ab_s: float,
    ladder_extra_s: float,
    spec_s: float,
    tp_overlap_s: float,
    proven_s: float,
    int4_s: float = 0.0,
    mixed_s: float = 0.0,
    prefix_s: float = 0.0,
    disagg_s: float = 0.0,
    pp_s: float = 0.0,
    serve_s: float = 0.0,
) -> dict:
    """Budget-aware phase trimming (pure — unit-tested in
    tests/test_bench.py). Given the seconds left on LLMQ_BENCH_DEADLINE
    and per-phase cost estimates, decide which phases run:

    - ``int4_ladder``: the int4+fp8 subprocess attempt (its timeout),
    - ``quant``: the int8+fp8 subprocess attempt (cost: its timeout),
    - ``kernel_ab``: the decode-kernel A/B subprocess (its timeout),
    - ``full_ladder``: every bf16 slot/decode-block candidate beyond the
      proven config (``ladder_extra_s`` extra build+measure cost),
    - ``spec_ladder``: the speculative-decoding rung at the winning
      (slots, K) point (``spec_s`` build+measure cost),
    - ``mixed_step``: the piggyback prefill+decode dispatch rung at the
      winning point (``mixed_s`` one extra build+measure),
    - ``tp_overlap``: the collective-matmul ring A/B at the winning
      point (``tp_overlap_s`` one extra build+measure; a no-op rung on
      single-device meshes),
    - ``prefix_rung``: the templated-traffic prefix-cache rung at the
      winning point (``prefix_s`` one extra build + a cold/warm pair),
    - ``disagg_rung``: the in-process two-pool prefill/decode A/B at the
      winning point (``disagg_s``: two extra builds + a unified
      reference pass + the pipelined handoff pass),
    - ``pp_rung``: the pipeline-parallel staged-engine rung at the
      winning point (``pp_s``: one extra build over the pp=2 mesh + a
      measure pass; a no-op rung on single-device meshes),
    - ``serve_rung``: the SLO priority-scheduling rung at the winning
      point (``serve_s``: one extra build + a FIFO-baseline pass and a
      priority pass over the same co-scheduled interactive+batch
      arrival trace).

    The proven bf16 headline (``proven_s``) is the floor and is never
    dropped — a bench that measures *something* always beats a watchdog
    0.0. Drop order is by speculation: the serve rung first (it prices
    the latency plane — interactive TTFT under batch load — and never
    touches the headline throughput number), then the pp rung (the model
    FITS one host here by construction — the rung only prices the
    bubble fraction and stage-boundary bytes a real multi-host pipeline
    would pay, never the headline number), then the disagg rung (purely
    diagnostic like the prefix rung, and the most builds per datapoint —
    it reports handoff latency and pool-split deltas, never the headline
    number), then the prefix rung (it reports a hit
    rate and never replaces the headline
    number, so shedding it loses telemetry, not the measurement), then
    the int4 attempt (deepest
    quantization, narrowest numerics margin — the rung most likely to
    be vetoed by its parity tier anyway), then the tp-overlap rung (it
    only matters on multi-chip slices and the worker's auto mode can
    A/B it out-of-band), then the int8 quant attempt (longest budget,
    most failure modes), then the spec rung (workload-dependent
    acceptance — the most likely rung to measure a loss), then the
    mixed-step rung (steady-state decode on synchronized bench arrivals
    understates it), then the extra ladder rungs, then the kernel A/B;
    each phase runs only if everything still planned fits the remaining
    budget. No deadline (None) runs everything.
    """
    # (name, cost) in DROP order: most speculative first.
    phases = (
        ("serve_rung", serve_s),
        ("pp_rung", pp_s),
        ("disagg_rung", disagg_s),
        ("prefix_rung", prefix_s),
        ("int4_ladder", int4_s),
        ("tp_overlap", tp_overlap_s),
        ("quant", quant_s),
        ("spec_ladder", spec_s),
        ("mixed_step", mixed_s),
        ("full_ladder", ladder_extra_s),
        ("kernel_ab", ab_s),
    )
    plan = {name: True for name, _ in phases}
    if remaining_s is None:
        return plan
    budget = remaining_s - proven_s  # the floor is reserved first
    for name, _cost in phases:
        if sum(c for n, c in phases if plan[n]) <= budget:
            break
        plan[name] = False
    return plan


def _try_quantized_headline(dtype: str = "int8") -> Optional[dict]:
    """Attempt a strong measured-candidate config — ``dtype`` (int8 or
    int4 group-quantized) weights + fp8 KV cache at the 3B preset — in a
    SUBPROCESS, and return its result line if it clearly clears the
    baseline.

    Why a child process: the quantized fast paths are CPU-validated but
    this may be the first time they touch the deployment chip (e.g.
    Mosaic could reject fp8 memrefs on some TPU generations) — a crash
    or hang must cost its budget, never the proven bf16 run. Why only
    ``vs_baseline >= 1.05``: below that the bf16 ladder might win, so
    the parent falls through and measures it. Opt out with
    ``LLMQ_BENCH_TRY_QUANT=0``.
    """
    import subprocess

    budget = float(os.environ.get("LLMQ_BENCH_QUANT_TIMEOUT", 1500))
    env = dict(
        os.environ,
        LLMQ_BENCH_DTYPE=dtype,
        LLMQ_BENCH_KV_DTYPE="fp8",
        LLMQ_BENCH_PRESET="qwen2.5-3b",
        LLMQ_BENCH_QUANT_CHILD="1",
        # The child's own watchdog fires just inside the subprocess
        # timeout so it can still print its JSON before the kill.
        LLMQ_BENCH_DEADLINE=str(max(60.0, budget - 20.0)),
    )
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            timeout=budget,
            capture_output=True,
            text=True,
            env=env,
        )
        sys.stderr.write(proc.stderr[-1500:])
        for line in reversed(proc.stdout.strip().splitlines()):
            if line.startswith("{"):
                payload = json.loads(line)
                if "error" in payload:
                    print(
                        f"bench: {dtype} attempt failed "
                        f"({payload['error'][:200]}); falling back to bf16",
                        file=sys.stderr,
                    )
                    return None
                return payload
        print(f"bench: {dtype} attempt printed no JSON", file=sys.stderr)
    except subprocess.TimeoutExpired:
        print(f"bench: {dtype} attempt timed out; bf16 run", file=sys.stderr)
    except Exception as exc:  # noqa: BLE001
        print(f"bench: {dtype} attempt error {exc!r}", file=sys.stderr)
    return None


def _fp8_kernel_canary() -> None:
    """On-device parity check of the compiled fp8-pool decode path
    against the XLA reference (same fp8 bits, both dequantize on load —
    any disagreement beyond dot-order noise means a miscompile).
    Raises on mismatch; the caller lets it crash the quant child."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from llmq_tpu.ops import dispatch

    if dispatch.resolve_backend() != "pallas":
        # LLMQ_ATTN_BACKEND=xla: the engine won't run a Pallas kernel,
        # so there is nothing to validate (and a Mosaic failure here
        # would spuriously kill a run that would have been fine).
        print("bench: fp8 canary skipped (xla backend)", file=sys.stderr)
        return

    from llmq_tpu.ops import attention as xla_ops

    S, H, NKV, D, PAGE, PPS, L = 8, 16, 2, 128, 128, 3, 2
    kq, kk, kv = jax.random.split(jax.random.key(7), 3)
    q = (jax.random.normal(kq, (S, H, D), jnp.float32) * 0.3).astype(
        jnp.bfloat16
    )
    P = 1 + S * PPS
    kp = (jax.random.normal(kk, (L, P, PAGE, NKV, D), jnp.float32) * 0.3)
    vp = (jax.random.normal(kv, (L, P, PAGE, NKV, D), jnp.float32) * 0.3)
    kp, vp = kp.astype(jnp.float8_e5m2), vp.astype(jnp.float8_e5m2)
    bt = jnp.arange(1, 1 + S * PPS, dtype=jnp.int32).reshape(S, PPS)
    cl = jnp.asarray([1, 40, 128, 129, 200, 255, 300, 332], jnp.int32)
    li = jnp.asarray(1, jnp.int32)
    kern, fused = dispatch.decode_kernel_plan(H, NKV)
    if fused:
        # v3 writes the step's fp8 K/V rows in-kernel — a DISTINCT code
        # path from plain decode; validate exactly what the engine runs.
        kn = (jax.random.normal(jax.random.key(8), (S, NKV, D),
                                jnp.float32) * 0.3).astype(jnp.bfloat16)
        vn = (jax.random.normal(jax.random.key(9), (S, NKV, D),
                                jnp.float32) * 0.3).astype(jnp.bfloat16)
        # Reference FIRST: the fused kernel aliases (donates) the pool
        # buffers, so kp/vp are unusable after it runs.
        positions = (cl - 1)[:, None]
        kp_r, vp_r = xla_ops.write_kv_pages(
            kp, vp, kn[:, None], vn[:, None], bt, positions, layer=li
        )
        ref = xla_ops.paged_decode_attention(
            q, kp_r, vp_r, bt, cl, scale=D**-0.5, layer=li
        )
        jax.block_until_ready(ref)
        out_p, kp_p, vp_p = dispatch.decode_attention_fused_write(
            q, kp, vp, kn, vn, bt, cl, scale=D**-0.5, layer=li
        )
        for name, got, want in (("K", kp_p, kp_r), ("V", vp_p, vp_r)):
            pool_err = np.max(
                np.abs(
                    np.asarray(got[li, 1:], np.float32)
                    - np.asarray(want[li, 1:], np.float32)
                )
            )
            if pool_err > 0:
                raise RuntimeError(
                    f"fp8 v3 canary: fused {name} write diverged "
                    f"(|diff| {pool_err})"
                )
        err = np.max(np.abs(np.asarray(out_p, np.float32) - np.asarray(ref, np.float32)))
    else:
        out_p = dispatch.decode_attention(
            q, kp, vp, bt, cl, scale=D**-0.5, backend="pallas", layer=li
        )
        ref = xla_ops.paged_decode_attention(
            q, kp, vp, bt, cl, scale=D**-0.5, layer=li
        )
        err = np.max(np.abs(np.asarray(out_p, np.float32) - np.asarray(ref, np.float32)))
    if not np.isfinite(err) or err > 0.05:
        raise RuntimeError(
            f"fp8 decode-kernel canary failed ({kern}): |pallas - xla| = {err}"
        )
    print(
        f"bench: fp8 kernel canary ok ({kern}, |diff| {err:.2e})",
        file=sys.stderr,
    )


def main() -> None:
    # Kernel A/B FIRST, while no backend is initialised in this process:
    # on standard TPU VMs libtpu is exclusive, so the probing child must
    # own the chip briefly and exit before the parent claims it. Gated on
    # a healthy backend probe so a dead tunnel costs one probe timeout,
    # not the A/B budget too.
    ab_choice = None
    # Budget-aware trimming: on a short remaining deadline the
    # speculative phases are dropped (quant attempt first, then extra
    # ladder rungs, then the kernel A/B) so the run always lands a real
    # bf16 measurement instead of a watchdog 0.0.
    plan = trim_plan(
        _remaining_budget(),
        quant_s=float(os.environ.get("LLMQ_BENCH_QUANT_TIMEOUT", 1500)),
        ab_s=float(os.environ.get("LLMQ_BENCH_AB_TIMEOUT", 420)),
        # Extra rungs beyond the proven config: one more slot count and
        # the decode-block ladder, ~4 min of builds+measures each.
        ladder_extra_s=720.0,
        # The spec rung re-measures the winning point twice (draft
        # length 2 then 4, early-stopped): ~2 builds + runs.
        spec_s=360.0,
        # The tp-overlap ring A/B is one extra build + measure at the
        # winning point (multi-chip slices only).
        tp_overlap_s=240.0,
        # The int4 subprocess attempt shares the quant-child budget but
        # drops first — it only runs on generous deadlines.
        int4_s=float(os.environ.get("LLMQ_BENCH_QUANT_TIMEOUT", 1500)),
        # The mixed-step rung is one extra build + measure at the
        # winning point.
        mixed_s=300.0,
        # The templated-traffic prefix rung is one extra build + a
        # short cold/warm pair at the winning point.
        prefix_s=240.0,
        # The disaggregated two-pool rung is three extra builds (unified
        # reference + prefill pool + decode pool) at the winning point.
        disagg_s=420.0,
        # The pipeline-parallel rung is one extra build (pp=2 staged
        # mesh, per-stage executables) + measure at the winning point.
        pp_s=300.0,
        # The serve rung is one extra build + two short co-scheduled
        # passes (FIFO baseline, then priority) at the winning point.
        serve_s=240.0,
        proven_s=300.0,
    )
    if not all(plan.values()):
        print(
            f"bench: deadline budget trims the plan to {plan}",
            file=sys.stderr,
        )
    quant_eligible = (
        plan["quant"]
        and os.environ.get("LLMQ_BENCH_TRY_QUANT", "1").lower()
        not in ("0", "false")
        and not os.environ.get("LLMQ_BENCH_QUANT_CHILD")
        and not os.environ.get("LLMQ_BENCH_DTYPE")
        and not os.environ.get("LLMQ_BENCH_KV_DTYPE")
        and not os.environ.get("LLMQ_BENCH_PRESET")
    )
    ab_eligible = plan["kernel_ab"] and not os.environ.get(
        "LLMQ_DECODE_KERNEL"
    )
    if (
        os.environ.get("JAX_PLATFORMS", "") != "cpu"
        and (quant_eligible or ab_eligible)
        and _probe_backend_subprocess(
            float(os.environ.get("LLMQ_BENCH_INIT_TIMEOUT", 120))
        )
    ):
        # Quantized-config attempts first (each owns the chip start to
        # finish, including its own kernel A/B at the fp8 pool dtype).
        # int8 always; the int4 ladder rung when the budget kept it (it
        # is the first phase trimmed) and not opted out. Skipped when
        # the operator pinned any of the knobs they would override —
        # explicit settings mean explicit intent.
        if quant_eligible:
            attempts = [_try_quantized_headline("int8")]
            if plan["int4_ladder"] and os.environ.get(
                "LLMQ_BENCH_TRY_INT4", "1"
            ).lower() not in ("0", "false"):
                attempts.append(_try_quantized_headline("int4"))
            attempts = [a for a in attempts if a is not None]
            quant = max(
                attempts, key=lambda p: p.get("vs_baseline", 0), default=None
            )
            if quant is not None and quant.get("vs_baseline", 0) >= 1.05:
                # Clear win over every bf16 number ever measured here
                # (best: 0.937): skip the bf16 run entirely.
                _emit(quant)
                return
            if quant is not None:
                # Not a clear win — measure bf16 too and emit the better.
                print(
                    f"bench: quantized attempt "
                    f"({quant.get('dtype')}) at "
                    f"{quant.get('vs_baseline')}x baseline; measuring bf16 "
                    "to compare",
                    file=sys.stderr,
                )
                global _QUANT_FALLBACK
                _QUANT_FALLBACK = quant
        if ab_eligible:
            ab_choice = pick_decode_kernel()
            # Export immediately: everything downstream — the fp8
            # canary included — must trace with the measured winner.
            os.environ["LLMQ_DECODE_KERNEL"] = ab_choice

    jax, devices, backend_note = init_devices()
    if jax is None or not devices:
        _emit_failure("none", backend_note or "no devices")
        return

    if (
        backend_note
        and backend_note.startswith("fell back to cpu")
        and os.environ.get("LLMQ_BENCH_CPU_FALLBACK_MEASURE", "") != "1"
    ):
        # The accelerator never came up. A tiny-preset CPU number would
        # be meaningless for the deployment — prefer the last line
        # actually measured on hardware (annotated, never silently), and
        # only measure the CPU fallback when there is no such line (or
        # the operator forces it with LLMQ_BENCH_CPU_FALLBACK_MEASURE=1).
        prior = _last_hardware_metric_line()
        if prior is not None:
            _emit(
                {
                    **prior,
                    "note": (
                        f"{backend_note}; re-emitting the last "
                        "hardware-measured line from PERF_RESULTS/ — NOT "
                        "measured this run"
                    ),
                    "backend": {
                        **_backend_stamp(
                            getattr(devices[0], "platform", "cpu"),
                            backend_note,
                        ),
                        "remeasured": False,
                    },
                }
            )
            return

    import jax.numpy as jnp
    import numpy as np

    from llmq_tpu.engine.engine import EngineConfig, EngineCore
    from llmq_tpu.engine.sampling import SamplingParams
    from llmq_tpu.engine.tokenizer import ByteTokenizer
    from llmq_tpu.models.presets import get_preset
    from llmq_tpu.models.transformer import init_params
    from llmq_tpu.parallel import make_mesh

    platform = devices[0].platform
    if os.environ.get("LLMQ_BENCH_QUANT_CHILD") and platform == "tpu":
        # Numerics canary: this may be the first time the fp8-pool
        # decode kernel meets the deployment chip. A Mosaic miscompile
        # would otherwise produce a *plausible throughput number from a
        # broken engine* — compare the compiled kernel against the XLA
        # reference on-device and abort (-> parent falls back to bf16)
        # rather than benchmark garbage.
        _fp8_kernel_canary()

    try:
        limit = (devices[0].memory_stats() or {}).get("bytes_limit")
    except Exception:  # noqa: BLE001
        limit = None
    # LLMQ_BENCH_DTYPE=int8 → weight-only quantization (bf16 compute):
    # halves weight HBM bytes/bandwidth and admits the 9B preset on
    # 16 GB. =int4 → AWQ-style per-group scale+zero quantization of the
    # layer matmuls (embed/lm_head stay int8): quarters the layer bytes.
    dtype_env = os.environ.get("LLMQ_BENCH_DTYPE", "").lower()
    int8 = dtype_env == "int8"
    int4 = dtype_env == "int4"
    preset = os.environ.get("LLMQ_BENCH_PRESET") or pick_preset(
        limit, platform, int8=int8, int4=int4
    )
    on_cpu = platform == "cpu"

    n_requests = int(os.environ.get("LLMQ_BENCH_REQUESTS", 8 if on_cpu else 576))
    prompt_len = int(os.environ.get("LLMQ_BENCH_PROMPT", 16 if on_cpu else 200))
    gen_len = int(os.environ.get("LLMQ_BENCH_GEN", 16 if on_cpu else 128))
    # Slot-count candidates for a ~3B model on one 16 GB chip: 256 OOMs
    # next to the weights, 128 leaves throughput behind. Unset → measure
    # BOTH 224 and 192 and keep the fastest (the ladder below runs the
    # headline at every candidate that fits; r05: 224 fit but ran ~3%
    # slower than 192).
    config = get_preset(preset)
    seqs_env = os.environ.get("LLMQ_BENCH_SEQS")
    if seqs_env:
        seqs_candidates = [int(seqs_env)]
    elif on_cpu:
        seqs_candidates = [4]
    elif int4 and config.num_params() > 5e9:
        # int4 leaves ~10 GB of KV next to a 9B model — roughly the
        # int8-3B regime; start the ladder above the int8-9B one.
        seqs_candidates = [160, 128, 96]
    elif int8 and config.num_params() > 5e9:
        # A ~9B int8 model leaves only ~5 GB for KV on a 16 GB chip
        # (fp8 KV doubles the tokens that buys): 3B-scale slot counts
        # would just burn builds on guaranteed OOMs.
        seqs_candidates = [96, 64]
    elif int4:
        # int4 quarters the weight bytes — even more KV headroom than
        # int8; start one rung above the int8 ladder.
        seqs_candidates = [288, 256, 224]
    elif int8:
        # int8 weights free ~3 GB next to a 3B model: 256 slots (which
        # OOMs at bf16) likely fits and amortizes the weight stream
        # further. The ladder early-stops on the throughput peak.
        seqs_candidates = [256, 224, 192]
    elif not plan["full_ladder"]:
        # Deadline-trimmed: no budget for extra rungs — measure only the
        # proven bf16 operating point.
        seqs_candidates = [_PROVEN_BF16_SEQS]
    else:
        seqs_candidates = [224, 192]
    dtype = jnp.float32 if on_cpu else jnp.bfloat16
    # Decode-block ladder: LLMQ_BENCH_DECODE_BLOCK pins K; otherwise the
    # winner slot count re-measures at K=2 and K=4 after the slot ladder
    # (budget permitting) and the best K is emitted.
    block_env = os.environ.get("LLMQ_BENCH_DECODE_BLOCK")
    block_pin = int(block_env) if block_env else None
    # Speculative-decoding rung: LLMQ_BENCH_SPEC_TOKENS pins the draft
    # length (every ladder build runs with it); otherwise the rung after
    # the decode-block ladder tries the prompt-lookup drafter and keeps
    # it only on a measured win.
    spec_env = os.environ.get("LLMQ_BENCH_SPEC_TOKENS")
    spec_pin = int(spec_env) if spec_env else None
    print(
        f"bench: preset={preset} ({config.num_params()/1e9:.2f}B) on "
        f"{len(devices)}x {platform}, {n_requests} reqs, "
        f"prompt {prompt_len}, gen {gen_len}",
        file=sys.stderr,
    )
    page_size = 8 if on_cpu else 128
    # quantize-at-init: the bf16 tree alone would not fit HBM at 9B.
    params = init_params(
        config, jax.random.key(0), dtype=dtype,
        quantize="int4" if int4 else int8,
    )
    mesh = make_mesh(devices=devices)  # all local devices, tp

    rng = np.random.default_rng(0)
    sp = lambda: SamplingParams(  # noqa: E731
        temperature=0.0, max_tokens=gen_len, ignore_eos=True
    )
    core = None

    def run(n, tag):
        for i in range(n):
            ids = rng.integers(1, config.vocab_size, size=prompt_len).tolist()
            core.add_request(f"{tag}-{i}", prompt_ids=ids, params=sp())
        done = 0
        start = time.monotonic()
        while core.has_work:
            done += len(core.step())
        elapsed = time.monotonic() - start
        assert done == n, f"{done}/{n} finished"
        return elapsed

    def is_oom(exc) -> bool:
        s = str(exc)
        return "RESOURCE_EXHAUSTED" in s or "out of memory" in s.lower()

    # Slot-count ladder: build + warm up + run the headline at EVERY
    # candidate that fits, and keep the fastest (r05 measurement: 224
    # slots built fine but ran ~3% slower than 192 — fitting is not
    # winning). OOM drops the candidate. The warmups force every
    # allocation and compile the timed run will hit — the B=1 prefill
    # variant, the padded max_prefill_batch variant, and the decode
    # step; a mid-run jit trace would otherwise eat tens of seconds of
    # the window.
    best = None  # (tok_s, max_seqs, out_tokens, elapsed)
    last_exc = None
    # Acceptance rate of the run that produced the headline number (0.0
    # whenever that run had spec_tokens=0).
    spec_rate = 0.0
    # Resolved tp_overlap mode of the run that produced the headline
    # number (the engine resolves env pin / auto at init).
    overlap_resolved = "off"
    # Ditto for the piggyback mixed-step dispatch mode, plus the
    # counters proving the winning run actually fused prefill work.
    mixed_resolved = "off"
    mixed_counts = (0, 0)  # (mixed_steps, mixed_prefill_tokens)
    # TTFT/ITL percentiles of the run that produced the headline number
    # (ms, from the engine's host-side histograms; empty until a rung
    # wins).
    lat_metrics: dict = {}

    def _latency_from_stats(stats: dict) -> dict:
        return {
            k: stats[k]
            for k in (
                "ttft_p50_ms", "ttft_p95_ms", "itl_p50_ms", "itl_p95_ms"
            )
            if stats.get(k) is not None
        }
    # LLMQ_BENCH_KV_DTYPE: "auto" (or empty) means "pick for me" — the
    # compute dtype, exactly like unset. Anything else names the pool
    # dtype explicitly ("fp8" -> float8_e5m2 pages, half the KV bytes;
    # see EngineConfig.kv_dtype).
    kv_env = (os.environ.get("LLMQ_BENCH_KV_DTYPE") or "").lower()
    kv_dtype = kv_env if kv_env not in ("", "auto") else dtype

    # Piggyback mixed-step dispatch: the engine refuses mixed_step=on
    # without prefill chunking, so any build that (or whose env pin)
    # turns it on also gets a chunk size.
    mixed_env = (os.environ.get("LLMQ_MIXED_STEP") or "").strip().lower()
    mixed_chunk = int(
        os.environ.get("LLMQ_BENCH_PREFILL_CHUNK", 64 if on_cpu else 256)
    )

    def build_core(
        max_seqs, block, spec=0, tp_overlap="off", mixed="off", prefix=False,
        mesh_override=None,
    ):
        return EngineCore(
            config,
            params,
            ByteTokenizer(),
            mesh=mesh_override if mesh_override is not None else mesh,
            engine_config=EngineConfig(
                max_num_seqs=max_seqs,
                max_model_len=1 << (prompt_len + gen_len + 2).bit_length(),
                kv_dtype=kv_dtype,
                num_pages=256 if on_cpu else None,
                # Chunked collective-matmul rings for the row-parallel
                # projections (ops/collective_matmul.py); the
                # LLMQ_TP_OVERLAP env pin overrides this inside the
                # engine either way.
                tp_overlap=tp_overlap,
                # Fused multi-step decode: K device iterations per host
                # dispatch (engine/engine.py decode_block).
                decode_block=block,
                # Lossless speculative decoding: prompt-lookup draft
                # tokens verified in one dispatch (0 = off).
                spec_tokens=spec,
                # Piggyback scheduling: fuse one prefill chunk into each
                # decode dispatch (engine/engine.py mixed_step).
                mixed_step=mixed,
                # Content-addressed prefix reuse (engine/scheduler.py):
                # only the templated-traffic rung turns it on — random
                # headline prompts share no prefixes to cache. Prefix
                # caching requires chunked prefill (the engine refuses
                # otherwise), so a prefix build also gets a chunk size.
                enable_prefix_caching=prefix,
                prefill_chunk_size=(
                    mixed_chunk
                    if (prefix or mixed == "on" or mixed_env == "on")
                    else None
                ),
                # 128-token pages: the decode kernel DMAs one page
                # per grid step, and 16 KB transfers are
                # latency-bound ~6x off the bandwidth floor (measured
                # round 2); 128-token pages make them 64 KB and
                # quarter the grid.
                page_size=page_size,
                # 8-prompt prefill chunks: 2048-token batches
                # amortize the weight stream ~24% better than the
                # default 4 (measured).
                max_prefill_batch=int(
                    os.environ.get(
                        "LLMQ_BENCH_PREFILL_BATCH", 2 if on_cpu else 8
                    )
                ),
            ),
        )

    for max_seqs in seqs_candidates:
        try:
            core = build_core(max_seqs, block_pin or 1, spec_pin or 0)
            run(1, "warmup-single")
            run(min(core.cfg.max_prefill_batch, n_requests), "warmup-batch")
            gen_before = core.total_generated_tokens
            elapsed = run(n_requests, f"bench-s{max_seqs}")
            out = core.total_generated_tokens - gen_before
            print(
                f"bench: {max_seqs} slots -> {out / elapsed:.1f} tok/s",
                file=sys.stderr,
            )
            if best is None or out / elapsed > best[0]:
                best = (out / elapsed, max_seqs, out, elapsed)
                win_stats = core.stats()
                spec_rate = win_stats.get("acceptance_rate", 0.0)
                lat_metrics = _latency_from_stats(win_stats)
                overlap_resolved = core.tp_overlap
                mixed_resolved = core.mixed_step
                mixed_counts = (
                    win_stats.get("mixed_steps", 0),
                    win_stats.get("mixed_prefill_tokens", 0),
                )
            elif out / elapsed < 0.98 * best[0]:
                # Throughput vs slot count is unimodal; once a candidate
                # measures clearly below the best (2% noise guard), the
                # smaller ones won't recover — stop paying builds.
                print(
                    f"bench: {max_seqs} slots past the peak; stopping "
                    "ladder",
                    file=sys.stderr,
                )
                core = None
                break
        except Exception as exc:  # noqa: BLE001 — skip only on OOM
            if not is_oom(exc):
                raise
            # Drop the traceback: its frames pin the partially-built
            # engine's device buffers, which the gc.collect() below must
            # free before the next (smaller) candidate builds.
            exc.__traceback__ = None
            last_exc = exc
            print(
                f"bench: {max_seqs} slots exhausted HBM; skipping",
                file=sys.stderr,
            )
        core = None
        import gc

        gc.collect()
    if best is None:
        raise last_exc or RuntimeError("no slot candidate fit")
    tok_s, max_seqs, out_tokens, elapsed = best

    # Decode-block ladder at the winning slot count: K=1 is already
    # measured (above); try the fused 2- and 4-iteration blocks and keep
    # the best. Skipped when K is pinned via env or the deadline trimmed
    # the ladder — the block rungs are exactly the kind of speculative
    # extra the trim plan exists to shed.
    best_block = block_pin or 1
    for block in [] if (block_pin or not plan["full_ladder"]) else [2, 4]:
        try:
            core = build_core(max_seqs, block, spec_pin or 0)
            run(1, "warmup-single")
            run(min(core.cfg.max_prefill_batch, n_requests), "warmup-batch")
            gen_before = core.total_generated_tokens
            b_elapsed = run(n_requests, f"bench-s{max_seqs}-k{block}")
            b_out = core.total_generated_tokens - gen_before
            b_tok_s = b_out / b_elapsed
            print(
                f"bench: {max_seqs} slots, decode block {block} -> "
                f"{b_tok_s:.1f} tok/s",
                file=sys.stderr,
            )
            if b_tok_s > tok_s:
                tok_s, out_tokens, elapsed, best_block = (
                    b_tok_s, b_out, b_elapsed, block
                )
                b_stats = core.stats()
                spec_rate = b_stats.get("acceptance_rate", 0.0)
                lat_metrics = _latency_from_stats(b_stats)
            elif b_tok_s < 0.98 * tok_s:
                # Larger K only adds wasted post-finish iterations on
                # top of whatever made this K lose; stop paying builds.
                print(
                    f"bench: decode block {block} past the peak; "
                    "stopping ladder",
                    file=sys.stderr,
                )
                core = None
                break
        except Exception as exc:  # noqa: BLE001 — skip only on OOM
            if not is_oom(exc):
                raise
            exc.__traceback__ = None
            print(
                f"bench: decode block {block} exhausted HBM; skipping",
                file=sys.stderr,
            )
        core = None
        import gc

        gc.collect()

    # Speculative-decoding rung at the winning (slots, K) point: try the
    # prompt-lookup drafter at 2 then 4 draft tokens and keep the best.
    # Early-stopped like the block ladder — acceptance is a property of
    # the workload, so once a draft length clearly loses, a longer one
    # (more wasted verify positions per rejection) won't recover.
    # Skipped when the draft length is pinned via LLMQ_BENCH_SPEC_TOKENS
    # (every build above already ran with it) or the deadline trimmed
    # the rung. Synthetic random prompts have little n-gram structure,
    # so a no-win outcome here is expected off-TPU; the rung pays off on
    # repetitive real workloads.
    best_spec = spec_pin or 0
    for spec in [] if (spec_pin or not plan["spec_ladder"]) else [2, 4]:
        try:
            core = build_core(max_seqs, best_block, spec)
            run(1, "warmup-single")
            run(min(core.cfg.max_prefill_batch, n_requests), "warmup-batch")
            gen_before = core.total_generated_tokens
            s_elapsed = run(n_requests, f"bench-s{max_seqs}-spec{spec}")
            s_out = core.total_generated_tokens - gen_before
            s_tok_s = s_out / s_elapsed
            s_stats = core.stats()
            s_rate = s_stats.get("acceptance_rate", 0.0)
            print(
                f"bench: {max_seqs} slots, spec {spec} -> "
                f"{s_tok_s:.1f} tok/s (acceptance {s_rate:.3f})",
                file=sys.stderr,
            )
            if s_tok_s > tok_s:
                tok_s, out_tokens, elapsed, best_spec, spec_rate = (
                    s_tok_s, s_out, s_elapsed, spec, s_rate
                )
                lat_metrics = _latency_from_stats(s_stats)
            elif s_tok_s < 0.98 * tok_s:
                print(
                    f"bench: spec {spec} past the peak; stopping ladder",
                    file=sys.stderr,
                )
                core = None
                break
        except Exception as exc:  # noqa: BLE001 — skip only on OOM
            if not is_oom(exc):
                raise
            exc.__traceback__ = None
            print(
                f"bench: spec {spec} exhausted HBM; skipping",
                file=sys.stderr,
            )
        core = None
        import gc

        gc.collect()

    # Mixed-step rung at the winning (slots, K, spec) point: re-measure
    # with piggyback prefill+decode dispatches on and keep the mode only
    # on a measured win. Skipped when the operator pinned
    # LLMQ_MIXED_STEP (every build above already resolved the pin) or
    # the deadline trimmed the rung. The bench's synchronized arrivals
    # understate the rung — its real payoff is prefill/decode
    # contention under streaming arrivals — so a no-win here is not a
    # veto of the mode, just of claiming it in the headline.
    if plan["mixed_step"] and not mixed_env:
        try:
            core = build_core(max_seqs, best_block, best_spec, mixed="on")
            run(1, "warmup-single")
            run(min(core.cfg.max_prefill_batch, n_requests), "warmup-batch")
            gen_before = core.total_generated_tokens
            m_elapsed = run(n_requests, f"bench-s{max_seqs}-mixed")
            m_out = core.total_generated_tokens - gen_before
            m_tok_s = m_out / m_elapsed
            m_stats = core.stats()
            print(
                f"bench: {max_seqs} slots, mixed_step on -> "
                f"{m_tok_s:.1f} tok/s (mixed_steps "
                f"{m_stats.get('mixed_steps', 0)}, piggybacked prefill "
                f"tokens {m_stats.get('mixed_prefill_tokens', 0)})",
                file=sys.stderr,
            )
            if m_tok_s > tok_s:
                tok_s, out_tokens, elapsed = m_tok_s, m_out, m_elapsed
                spec_rate = m_stats.get("acceptance_rate", 0.0)
                lat_metrics = _latency_from_stats(m_stats)
                mixed_resolved = "on"
                mixed_counts = (
                    m_stats.get("mixed_steps", 0),
                    m_stats.get("mixed_prefill_tokens", 0),
                )
        except Exception as exc:  # noqa: BLE001 — skip only on OOM
            if not is_oom(exc):
                raise
            exc.__traceback__ = None
            print(
                "bench: mixed_step rung exhausted HBM; skipping",
                file=sys.stderr,
            )
        core = None
        import gc

        gc.collect()

    # Tensor-parallel overlap rung at the winning (slots, K, spec)
    # point: re-measure with the chunked collective-matmul rings on and
    # keep the mode only on a measured win. Skipped off multi-chip
    # meshes, when the operator pinned LLMQ_TP_OVERLAP (every build
    # above already resolved it), or when the deadline trimmed the rung.
    from llmq_tpu.parallel.mesh import DP_AXIS, SP_AXIS, TP_AXIS

    overlap_eligible = (
        plan["tp_overlap"]
        and int(mesh.shape[TP_AXIS]) > 1
        and not (os.environ.get("LLMQ_TP_OVERLAP") or "").strip()
        and overlap_resolved == "off"
    )
    if overlap_eligible:
        try:
            core = build_core(
                max_seqs, best_block, best_spec,
                tp_overlap="on", mixed=mixed_resolved,
            )
            run(1, "warmup-single")
            run(min(core.cfg.max_prefill_batch, n_requests), "warmup-batch")
            gen_before = core.total_generated_tokens
            o_elapsed = run(n_requests, f"bench-s{max_seqs}-tpovl")
            o_out = core.total_generated_tokens - gen_before
            o_tok_s = o_out / o_elapsed
            print(
                f"bench: {max_seqs} slots, tp_overlap on -> "
                f"{o_tok_s:.1f} tok/s",
                file=sys.stderr,
            )
            if o_tok_s > tok_s:
                tok_s, out_tokens, elapsed = o_tok_s, o_out, o_elapsed
                o_stats = core.stats()
                spec_rate = o_stats.get("acceptance_rate", 0.0)
                lat_metrics = _latency_from_stats(o_stats)
                overlap_resolved = core.tp_overlap
        except Exception as exc:  # noqa: BLE001 — skip only on OOM
            if not is_oom(exc):
                raise
            exc.__traceback__ = None
            print(
                "bench: tp_overlap rung exhausted HBM; skipping",
                file=sys.stderr,
            )
        core = None
        import gc

        gc.collect()

    # Templated-traffic prefix rung at the winning (slots, K, spec)
    # point: real fleets serve prompts that share a long template
    # (system prompt, few-shot preamble), which the random headline
    # prompts cannot represent. Build once more with the prefix cache
    # on, seed the template's pages with a single cold request, then
    # run a batch whose prompts all share that template — the warm
    # pass must *reuse* the pages, not recompute them. Purely
    # diagnostic: synchronized arrivals + one shared template are the
    # cache's best case, so the warm tok/s never replaces the
    # headline; the rung's product is the measured hit rate and the
    # prefill_tokens fraction proving cached positions were skipped.
    prefix_metrics: dict = {}
    if plan["prefix_rung"] and os.environ.get(
        "LLMQ_BENCH_TRY_PREFIX", "1"
    ).lower() not in ("0", "false"):
        try:
            core = build_core(
                max_seqs, best_block, best_spec,
                mixed=mixed_resolved, prefix=True,
            )
            # Shared template: ~3/4 of the prompt, rounded down to the
            # page size so whole pages land in the cache; random
            # per-request tails keep the suffix (and sampling) honest.
            tmpl_len = max(
                page_size, (prompt_len * 3 // 4) // page_size * page_size
            )
            template_ids = rng.integers(
                1, config.vocab_size, size=tmpl_len
            ).tolist()

            def run_templated(n, tag):
                for i in range(n):
                    tail = rng.integers(
                        1, config.vocab_size, size=prompt_len - tmpl_len
                    ).tolist()
                    core.add_request(
                        f"{tag}-{i}",
                        prompt_ids=template_ids + tail,
                        params=sp(),
                    )
                done = 0
                start = time.monotonic()
                while core.has_work:
                    done += len(core.step())
                assert done == n, f"{done}/{n} finished"
                return time.monotonic() - start

            n_prefix = min(n_requests, max(core.cfg.max_prefill_batch, 8))
            # Cold pass: compiles the chunked-prefill variants AND
            # registers the template's pages — everything after it is
            # the steady state a templated fleet lives in.
            run_templated(1, "prefix-cold")
            hits0 = core.scheduler.prefix_hits
            miss0 = core.scheduler.prefix_misses
            prefill0 = core.prefill_tokens
            gen_before = core.total_generated_tokens
            p_elapsed = run_templated(n_prefix, "prefix-warm")
            p_out = core.total_generated_tokens - gen_before
            hits = core.scheduler.prefix_hits - hits0
            seen = hits + (core.scheduler.prefix_misses - miss0)
            hit_rate = hits / seen if seen else 0.0
            # Fraction of warm prompt positions actually computed —
            # (1 - tmpl/prompt) when every template page hit.
            prefill_frac = (core.prefill_tokens - prefill0) / (
                n_prefix * prompt_len
            )
            print(
                f"bench: prefix rung ({n_prefix} templated reqs, "
                f"template {tmpl_len}/{prompt_len} tokens) -> hit rate "
                f"{hit_rate:.3f}, prefill frac {prefill_frac:.3f}, "
                f"{p_out / p_elapsed:.1f} tok/s warm",
                file=sys.stderr,
            )
            prefix_metrics = {
                "prefix_hit_rate": round(float(hit_rate), 4),
                "prefix_prefill_frac": round(float(prefill_frac), 4),
                "prefix_warm_tok_s_chip": round(
                    p_out / p_elapsed / len(devices), 2
                ),
            }
        except Exception as exc:  # noqa: BLE001 — skip only on OOM
            if not is_oom(exc):
                raise
            exc.__traceback__ = None
            print(
                "bench: prefix rung exhausted HBM; skipping",
                file=sys.stderr,
            )
        core = None
        import gc

        gc.collect()

    # Disaggregated two-pool rung at the winning (slots, K, spec) point:
    # split the winning slot budget across a prefill-role engine and a
    # decode-role engine, run templated traffic through the real phase
    # boundary (prefill_only request -> snapshot codec round-trip ->
    # insert_request adoption on the decode engine), and A/B against a
    # unified engine serving the identical prompts. Diagnostic like the
    # prefix rung: its product is the handoff cost (codec + insert) and
    # the TTFT/ITL deltas of pool separation, never the headline number —
    # an in-process A/B can't model the network hop between real pools,
    # so the deltas here are the *floor* of disaggregation's cost.
    disagg_metrics: dict = {}
    if plan["disagg_rung"] and os.environ.get(
        "LLMQ_BENCH_TRY_DISAGG", "1"
    ).lower() not in ("0", "false"):
        try:
            import gc

            from llmq_tpu.engine.snapshot import (
                snapshot_from_b64,
                snapshot_to_b64,
            )

            def _p50(vals):
                ordered = sorted(vals)
                return ordered[len(ordered) // 2] if ordered else None

            tmpl_len = max(
                page_size, (prompt_len * 3 // 4) // page_size * page_size
            )
            d_template = rng.integers(
                1, config.vocab_size, size=tmpl_len
            ).tolist()
            pool_seqs = max(2, max_seqs // 2)
            n_disagg = min(n_requests, max(2 * pool_seqs, 8))
            d_prompts = [
                d_template
                + rng.integers(
                    1, config.vocab_size, size=prompt_len - tmpl_len
                ).tolist()
                for _ in range(n_disagg)
            ]

            # Unified reference on the SAME prompts at the same pool
            # size, so the A/B isolates the phase split (not slot count
            # or traffic shape).
            core = build_core(pool_seqs, best_block, best_spec,
                              mixed=mixed_resolved)
            core.add_request("dsu-warm", prompt_ids=d_prompts[0], params=sp())
            while core.has_work:
                core.step()
            u_gen0 = core.total_generated_tokens
            u_start = time.monotonic()
            for i, ids in enumerate(d_prompts):
                core.add_request(f"dsu-{i}", prompt_ids=ids, params=sp())
            u_done = 0
            while core.has_work:
                u_done += len(core.step())
            u_elapsed = time.monotonic() - u_start
            assert u_done == n_disagg, f"{u_done}/{n_disagg} unified"
            u_out = core.total_generated_tokens - u_gen0
            u_stats = core.stats()
            u_tok_s = u_out / u_elapsed
            core = None
            gc.collect()

            pre = build_core(pool_seqs, best_block, 0)
            dec = build_core(pool_seqs, best_block, best_spec)

            def _handoff(out, stamps):
                """Snapshot codec round-trip + adoption insert — the
                in-process equivalent of the ship/snapshot paths."""
                t0 = time.monotonic()
                snap = snapshot_from_b64(snapshot_to_b64(out.snapshot))
                dec.insert_request(snap)
                stamps.append((time.monotonic() - t0) * 1000.0)

            # Warm both pools through the full boundary (compiles the
            # prefill-only path, the codec, and the adoption insert).
            pre.add_request(
                "dsw", prompt_ids=d_prompts[0], params=sp(),
                prefill_only=True,
            )
            warm_ms: list = []
            while pre.has_work or dec.has_work:
                for out in pre.step() if pre.has_work else ():
                    if out.snapshot is not None:
                        _handoff(out, warm_ms)
                if dec.has_work:
                    dec.step()

            handoff_ms: list = []
            adopt_wall: dict = {}
            d_gen0 = dec.total_generated_tokens
            d_start = time.monotonic()
            for i, ids in enumerate(d_prompts):
                pre.add_request(
                    f"dsd-{i}", prompt_ids=ids, params=sp(),
                    prefill_only=True,
                )
            d_done = 0
            while pre.has_work or dec.has_work:
                for out in pre.step() if pre.has_work else ():
                    if out.finish_reason == "prefill_done" and (
                        out.snapshot is not None
                    ):
                        _handoff(out, handoff_ms)
                        adopt_wall[out.rid] = time.monotonic() - d_start
                if dec.has_work:
                    d_done += len(dec.step())
            d_elapsed = time.monotonic() - d_start
            assert d_done == n_disagg, f"{d_done}/{n_disagg} adopted"
            d_out = dec.total_generated_tokens - d_gen0
            d_stats = dec.stats()
            d_tok_s = d_out / d_elapsed
            # Submit-to-first-token for an adopted request spans both
            # pools: prefill span (all requests submitted at d_start) +
            # the decode engine's insert->first-token TTFT.
            pre_span_p50 = _p50(list(adopt_wall.values()))
            disagg_metrics = {
                "disagg_tok_s_chip": round(d_tok_s / len(devices), 2),
                "disagg_vs_unified": round(d_tok_s / u_tok_s, 4),
            }
            p50 = _p50(handoff_ms)
            if p50 is not None:
                disagg_metrics["handoff_ms_p50"] = round(p50, 3)
                disagg_metrics["handoff_ms_p95"] = round(
                    sorted(handoff_ms)[
                        min(len(handoff_ms) - 1,
                            int(0.95 * len(handoff_ms)))
                    ],
                    3,
                )
            if (
                pre_span_p50 is not None
                and d_stats.get("ttft_p50_ms") is not None
                and u_stats.get("ttft_p50_ms") is not None
            ):
                disagg_metrics["disagg_ttft_p50_delta_ms"] = round(
                    pre_span_p50 * 1000.0
                    + d_stats["ttft_p50_ms"]
                    - u_stats["ttft_p50_ms"],
                    3,
                )
            if (
                d_stats.get("itl_p50_ms") is not None
                and u_stats.get("itl_p50_ms") is not None
            ):
                disagg_metrics["disagg_itl_p50_delta_ms"] = round(
                    d_stats["itl_p50_ms"] - u_stats["itl_p50_ms"], 3
                )
            print(
                f"bench: disagg rung ({n_disagg} templated reqs, "
                f"{pool_seqs}+{pool_seqs} slots) -> "
                f"{d_tok_s:.1f} tok/s vs {u_tok_s:.1f} unified, "
                f"handoff p50 "
                f"{disagg_metrics.get('handoff_ms_p50', 0.0)} ms",
                file=sys.stderr,
            )
            pre = dec = None
        except Exception as exc:  # noqa: BLE001 — skip only on OOM
            if not is_oom(exc):
                raise
            exc.__traceback__ = None
            print(
                "bench: disagg rung exhausted HBM; skipping",
                file=sys.stderr,
            )
        core = None
        import gc

        gc.collect()

    # Pipeline-parallel rung at the winning (slots, K) point: rebuild
    # over a pp=2 mesh (layer stack split across two stage submeshes,
    # activations hopping the boundary host-driven) and re-measure the
    # headline workload. Diagnostic: the model FITS one host here by
    # construction, so the rung's product is the measured cost of
    # staging — tok/s vs the single-stage number, the GPipe bubble
    # fraction of the run's actual microbatching, and the
    # stage-boundary activation bytes per generated token (the floor of
    # what a real cross-host DCN hop would carry). Spec decoding stays
    # off (the staged engine gates it) and the rung never replaces the
    # headline.
    pp_metrics: dict = {}
    if (
        plan["pp_rung"]
        and len(devices) >= 2
        and os.environ.get("LLMQ_BENCH_TRY_PP", "1").lower()
        not in ("0", "false")
    ):
        try:
            pp_mesh = make_mesh(devices=devices, pipeline_parallel=2)
            core = build_core(
                max_seqs, best_block, 0, mixed=mixed_resolved,
                mesh_override=pp_mesh,
            )
            run(1, "warmup-single")
            run(min(core.cfg.max_prefill_batch, n_requests), "warmup-batch")
            gen_before = core.total_generated_tokens
            bytes_before = core.pp_boundary_bytes
            pp_elapsed = run(n_requests, f"bench-s{max_seqs}-pp2")
            pp_out = core.total_generated_tokens - gen_before
            pp_tok_s = pp_out / pp_elapsed
            pp_stats = core.stats()
            pp_bytes_tok = (core.pp_boundary_bytes - bytes_before) / pp_out
            pp_metrics = {
                "pp_stages": int(pp_stats["pp_stages"]),
                "pp_tok_s_chip": round(pp_tok_s / len(devices), 2),
                "pp_vs_unified": round(pp_tok_s / tok_s, 4),
                "pp_bubble_fraction": round(
                    float(pp_stats["pp_bubble_fraction"]), 4
                ),
                "pp_boundary_bytes_per_token": round(pp_bytes_tok, 1),
            }
            print(
                f"bench: pp rung ({pp_stats['pp_stages']} stages) -> "
                f"{pp_tok_s:.1f} tok/s "
                f"({pp_metrics['pp_vs_unified']}x single-stage), bubble "
                f"{pp_metrics['pp_bubble_fraction']}, "
                f"{pp_metrics['pp_boundary_bytes_per_token']} boundary "
                f"bytes/token",
                file=sys.stderr,
            )
        except Exception as exc:  # noqa: BLE001 — skip only on OOM
            if not is_oom(exc):
                raise
            exc.__traceback__ = None
            print(
                "bench: pp rung exhausted HBM; skipping",
                file=sys.stderr,
            )
        core = None
        import gc

        gc.collect()

    # SLO serve rung at the winning (slots, K) point: co-schedule a
    # saturating batch workload with a trickle of short interactive
    # requests over the SAME arrival trace twice — once with every
    # request labeled batch (FIFO baseline) and once with the trickle
    # labeled interactive (priority admission + preemption). The product
    # is the interactive TTFT p95 under load and what the priority path
    # costs the batch plane — diagnostics, never the headline. The FIFO
    # pass runs FIRST so the engine's lazily-enabled priority plane
    # can't leak into the baseline.
    serve_metrics: dict = {}
    if (
        plan["serve_rung"]
        and os.environ.get("LLMQ_BENCH_TRY_SERVE", "1").lower()
        not in ("0", "false")
    ):
        try:
            core = build_core(max_seqs, best_block, 0, mixed=mixed_resolved)
            run(1, "warmup-single")
            run(min(core.cfg.max_prefill_batch, n_requests), "warmup-batch")

            def serve_pass(tag, interactive):
                srng = np.random.default_rng(7)
                n_batch = max(max_seqs * 2, 8)
                n_int = 16
                int_prompt = max(8, prompt_len // 4)
                for i in range(n_batch):
                    ids = srng.integers(
                        1, config.vocab_size, size=prompt_len
                    ).tolist()
                    core.add_request(f"{tag}-b{i}", prompt_ids=ids, params=sp())
                ttfts, added, steps = [], 0, 0
                gen_before = core.total_generated_tokens
                start = time.monotonic()
                while core.has_work or added < n_int:
                    if added < n_int and steps % 8 == 0:
                        ids = srng.integers(
                            1, config.vocab_size, size=int_prompt
                        ).tolist()
                        core.add_request(
                            f"{tag}-i{added}",
                            prompt_ids=ids,
                            params=SamplingParams(
                                temperature=0.0, max_tokens=16,
                                ignore_eos=True,
                            ),
                            priority=(
                                "interactive" if interactive else "batch"
                            ),
                        )
                        added += 1
                    for out in core.step():
                        t = out.timing or {}
                        if out.rid.startswith(f"{tag}-i") and (
                            "first_token" in t and "enqueued" in t
                        ):
                            ttfts.append(t["first_token"] - t["enqueued"])
                    steps += 1
                elapsed = time.monotonic() - start
                out_tok = core.total_generated_tokens - gen_before
                batch_tok_s = (out_tok - n_int * 16) / elapsed
                ttfts.sort()
                p95 = (
                    ttfts[min(len(ttfts) - 1, int(0.95 * len(ttfts)))]
                    if ttfts
                    else 0.0
                )
                return p95 * 1000.0, batch_tok_s

            fifo_ttft_ms, fifo_tok_s = serve_pass("sf", interactive=False)
            prio_ttft_ms, prio_tok_s = serve_pass("sp", interactive=True)
            serve_metrics = {
                "ttft_p95_interactive": round(prio_ttft_ms, 1),
                "ttft_p95_interactive_fifo": round(fifo_ttft_ms, 1),
                "batch_tok_s": round(prio_tok_s, 1),
                "batch_tok_s_fifo": round(fifo_tok_s, 1),
                "priority_preemptions": int(
                    core.stats().get("priority_preemptions", 0)
                ),
            }
            print(
                f"bench: serve rung -> interactive ttft p95 "
                f"{prio_ttft_ms:.0f} ms (fifo {fifo_ttft_ms:.0f} ms), "
                f"batch {prio_tok_s:.1f} tok/s "
                f"(fifo {fifo_tok_s:.1f}), "
                f"{serve_metrics['priority_preemptions']} preemptions",
                file=sys.stderr,
            )
        except Exception as exc:  # noqa: BLE001 — skip only on OOM
            if not is_oom(exc):
                raise
            exc.__traceback__ = None
            print(
                "bench: serve rung exhausted HBM; skipping",
                file=sys.stderr,
            )
        core = None
        import gc

        gc.collect()

    tok_s_chip = tok_s / len(devices)
    # MoE presets: throughput scales with ACTIVE params per token (the
    # FLOPs actually spent), not the total parameter count.
    active = config.active_params_per_token()
    baseline = 1500.0 * 9e9 / active
    mfu = (tok_s * 2.0 * active) / (
        peak_flops_per_chip(devices) * len(devices)
    )
    payload = {
        "metric": f"decode_tokens_per_sec_per_chip[{preset}]",
        "value": round(tok_s_chip, 2),
        "unit": "tok/s/chip",
        "vs_baseline": round(tok_s_chip / baseline, 4),
        "mfu": round(mfu, 4),
        "dtype": "int4" if int4 else ("int8" if int8 else str(jnp.dtype(dtype))),
        "max_seqs": max_seqs,
        "decode_block": best_block,
        "spec_tokens": best_spec,
        "acceptance_rate": round(float(spec_rate), 4),
        # TTFT/ITL percentiles (ms) from the winning rung's engine
        # histograms — absent only if the engine reported none.
        **{
            out_key: round(float(lat_metrics[in_key]), 3)
            for out_key, in_key in (
                ("ttft_p50", "ttft_p50_ms"),
                ("ttft_p95", "ttft_p95_ms"),
                ("itl_p50", "itl_p50_ms"),
                ("itl_p95", "itl_p95_ms"),
            )
            if in_key in lat_metrics
        },
        "mixed_step": mixed_resolved,
        **(
            {
                "mixed_steps": int(mixed_counts[0]),
                "mixed_prefill_tokens": int(mixed_counts[1]),
            }
            if mixed_resolved == "on"
            else {}
        ),
        "mesh": {
            "dp": int(mesh.shape[DP_AXIS]),
            "sp": int(mesh.shape[SP_AXIS]),
            "tp": int(mesh.shape[TP_AXIS]),
        },
        "tp_overlap": overlap_resolved,
        # Templated-traffic prefix rung (absent when trimmed/opted out):
        # hit rate, computed-prefill fraction, and the best-case warm
        # throughput — diagnostics, never the headline.
        **prefix_metrics,
        # Disaggregated two-pool rung (absent when trimmed/opted out):
        # pool-split throughput, handoff codec+insert latency, and the
        # TTFT/ITL deltas vs the unified reference — diagnostics too.
        **disagg_metrics,
        # Pipeline-parallel rung (absent when trimmed/opted out/single
        # device): staged-engine throughput vs single-stage, GPipe
        # bubble fraction, and stage-boundary bytes/token — diagnostics.
        **pp_metrics,
        # SLO serve rung (absent when trimmed/opted out): interactive
        # TTFT p95 under co-scheduled batch load, priority vs FIFO, and
        # the batch-throughput cost of priority — diagnostics.
        **serve_metrics,
        **(
            {"kv_dtype": kv_env}
            if kv_env not in ("", "auto")
            else {}
        ),
        "decode_kernel": ab_choice or os.environ.get("LLMQ_DECODE_KERNEL") or "v1",
    }
    if backend_note:
        payload["note"] = backend_note
    if (
        _QUANT_FALLBACK is not None
        and _QUANT_FALLBACK.get("vs_baseline", 0) > payload["vs_baseline"]
    ):
        payload = _QUANT_FALLBACK
    payload["backend"] = _backend_stamp(platform, backend_note)
    _emit(payload)


if __name__ == "__main__" and "--kernel-ab-probe" in sys.argv:
    _kernel_ab_probe_main()
elif __name__ == "__main__":
    # Whole-run watchdog: a tunnel can also wedge *after* init (first jit
    # compile / dispatch blocks in C). If the run exceeds the deadline,
    # the failure JSON still gets emitted before exiting.
    _deadline = float(os.environ.get("LLMQ_BENCH_DEADLINE", 3600))
    _cancel = _arm_emit_watchdog(
        _deadline,
        "benchmark exceeded LLMQ_BENCH_DEADLINE (device dispatch hung?)",
    )
    # trim_plan() measures the remaining budget against this deadline.
    _DEADLINE_AT = time.monotonic() + _deadline
    try:
        main()
    except Exception as exc:  # noqa: BLE001 — the JSON line must print
        import traceback

        traceback.print_exc()
        _emit_failure("failed", f"{type(exc).__name__}: {exc}")
    finally:
        _cancel()
