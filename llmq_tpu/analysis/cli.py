"""Entry point shared by ``python -m llmq_tpu.analysis`` and ``llmq-tpu lint``.

Exit codes: 0 clean (warnings allowed unless ``--strict``), 1 violations,
2 usage error. Kept on argparse so the analyzer stays importable with zero
third-party dependencies (CI images, pre-commit hooks).
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional, Sequence

from llmq_tpu.analysis.checkers import RULES
from llmq_tpu.analysis.core import AnalysisContext, analyze_paths
from llmq_tpu.analysis.reporters import render_json, render_sarif, render_text


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="llmq-tpu lint",
        description="Project-specific AST lint for the broker/worker/engine stack.",
    )
    parser.add_argument(
        "paths",
        nargs="*",
        default=["llmq_tpu"],
        help="files or directories to analyze (default: llmq_tpu)",
    )
    parser.add_argument(
        "--format",
        choices=("text", "json", "sarif"),
        default="text",
        help="report format (sarif = SARIF 2.1.0 for CI diff annotation)",
    )
    parser.add_argument(
        "--spmd",
        action="store_true",
        help="also run the tier-B SPMD repartition diff gate (lowers the "
        "tiny-preset programs over the mesh matrix in a subprocess with "
        "8 virtual CPU devices and diffs collective signatures against "
        "the recorded baseline)",
    )
    parser.add_argument(
        "--spmd-record",
        action="store_true",
        help="re-record the SPMD collective-signature baseline instead of "
        "diffing (implies --spmd)",
    )
    parser.add_argument(
        "--strict",
        action="store_true",
        help="treat warnings as failures",
    )
    parser.add_argument(
        "--select",
        action="append",
        default=None,
        metavar="RULE",
        help="only run these rule ids (repeatable)",
    )
    parser.add_argument(
        "--ignore",
        action="append",
        default=None,
        metavar="RULE",
        help="skip these rule ids (repeatable)",
    )
    parser.add_argument(
        "--hot-path",
        action="append",
        default=None,
        metavar="NAME",
        help="extra hot-path function name ('step' or 'EngineCore.step') "
        "for jax-host-sync (repeatable)",
    )
    parser.add_argument(
        "--list-rules",
        action="store_true",
        help="print every rule id with severity and summary, then exit",
    )
    return parser


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in sorted(RULES.values(), key=lambda r: r.id):
            print(f"{rule.id:20s} {rule.severity:8s} {rule.summary}")
        return 0

    known = set(RULES) | {"parse-error"}
    for opt_name, ids in (("--select", args.select), ("--ignore", args.ignore)):
        for rule_id in ids or []:
            if rule_id not in known:
                print(
                    f"error: unknown rule id {rule_id!r} for {opt_name} "
                    f"(see --list-rules)",
                    file=sys.stderr,
                )
                return 2

    ctx = AnalysisContext(hot_paths=set(args.hot_path or []))
    violations = analyze_paths(
        args.paths,
        ctx=ctx,
        select=set(args.select) if args.select else None,
        ignore=set(args.ignore) if args.ignore else None,
    )
    renderer = {
        "json": render_json,
        "sarif": render_sarif,
        "text": render_text,
    }[args.format]
    print(renderer(violations))
    failing: List = [
        v
        for v in violations
        if v.severity == "error" or (args.strict and v.severity == "warning")
    ]
    rc = 1 if failing else 0

    if args.spmd or args.spmd_record:
        from llmq_tpu.analysis.spmd import run_gate_subprocess

        spmd_rc = run_gate_subprocess(record=args.spmd_record)
        rc = max(rc, spmd_rc)
    return rc


if __name__ == "__main__":
    sys.exit(main())
