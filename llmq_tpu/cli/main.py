"""CLI root & command definitions.

Counterpart of reference ``llmq/cli/main.py:6-549``: ``submit``, ``receive``,
``status``, ``health``, ``errors``, ``clear``, and the ``worker`` subgroup —
plus the llmq-tpu-only ``broker`` subgroup (the reference assumed an external
RabbitMQ; we ship the daemon).

Heavy imports (jax, engine, submit machinery) are deferred into command
bodies so ``--help`` stays instant (same lazy-import pattern as the
reference).
"""

from __future__ import annotations

import asyncio
import os
import sys
from typing import Optional, Tuple

import click

from llmq_tpu._version import __version__


def _parse_maps(map_args: Tuple[str, ...]) -> dict:
    """Parse repeated ``--map field=SPEC`` options (reference main.py:104-113)."""
    from llmq_tpu.core.template import parse_map_spec

    mapping = {}
    for raw in map_args:
        if "=" not in raw:
            raise click.BadParameter(
                f"--map must be field=TEMPLATE, got {raw!r}", param_hint="--map"
            )
        field, _, spec = raw.partition("=")
        mapping[field.strip()] = parse_map_spec(spec)
    return mapping


@click.group()
@click.version_option(version=__version__, prog_name="llmq-tpu")
def cli() -> None:
    """llmq-tpu: TPU-native queue-based LLM batch inference."""


# ---------------------------------------------------------------------------
# submit / receive
# ---------------------------------------------------------------------------


@cli.command()
@click.argument("queue_or_pipeline")
@click.argument("source")
@click.option("--map", "map_args", multiple=True, help="field=TEMPLATE mapping")
@click.option("-p", "--pipeline", "is_pipeline", is_flag=True, help="QUEUE arg is a pipeline YAML")
@click.option("--stream", is_flag=True, help="Stream results to stdout while submitting")
@click.option("--split", default="train", show_default=True, help="HF dataset split")
@click.option("--subset", default=None, help="HF dataset subset/config name")
@click.option("--limit", type=int, default=None, help="Submit at most N jobs")
@click.option("--priority", type=click.Choice(["interactive", "batch"]),
              default=None,
              help="SLO class stamped on every job (row-level fields win); "
                   "interactive rides the fast lane and preempts batch work")
def submit(queue_or_pipeline, source, map_args, is_pipeline, stream, split, subset, limit, priority):
    """Submit jobs from a JSONL file, '-' (stdin), or an HF dataset.

    QUEUE_OR_PIPELINE is a queue name, or with -p a pipeline YAML path.
    """
    from llmq_tpu.cli.submit import run_pipeline_submit, run_submit

    mapping = _parse_maps(map_args)
    if is_pipeline:
        if priority is not None:
            mapping.setdefault("priority", priority)
        asyncio.run(
            run_pipeline_submit(
                queue_or_pipeline, source, mapping,
                stream=stream, split=split, subset=subset, limit=limit,
            )
        )
    else:
        asyncio.run(
            run_submit(
                queue_or_pipeline, source, mapping,
                stream=stream, split=split, subset=subset, limit=limit,
                priority=priority,
            )
        )


@cli.command()
@click.argument("queue_or_pipeline")
@click.option("-p", "--pipeline", "is_pipeline", is_flag=True, help="Arg is a pipeline YAML")
@click.option("--timeout", type=float, default=None,
              help="Idle timeout seconds (exit when no results)")
@click.option("--limit", type=int, default=None, help="Stop after N results")
def receive(queue_or_pipeline, is_pipeline, timeout, limit):
    """Receive results as JSONL on stdout."""
    from llmq_tpu.cli.receive import run_pipeline_receive, run_receive

    if is_pipeline:
        asyncio.run(run_pipeline_receive(queue_or_pipeline, timeout=timeout, limit=limit))
    else:
        asyncio.run(run_receive(queue_or_pipeline, timeout=timeout, limit=limit))


# Deprecated aliases (reference cli/main.py:152-254,375-408 parity):
# `pipeline` / `receive-pipeline` predate the unified -p flag.


@cli.command("pipeline", hidden=True)
@click.argument("pipeline_path")
@click.argument("source")
@click.option("--map", "map_args", multiple=True)
@click.option("--stream", is_flag=True)
@click.option("--split", default="train", show_default=True)
@click.option("--subset", default=None)
@click.option("--limit", type=int, default=None)
def pipeline_deprecated(pipeline_path, source, map_args, stream, split,
                        subset, limit):
    """[deprecated] Use `submit -p PIPELINE.yaml SOURCE`."""
    from llmq_tpu.cli.submit import run_pipeline_submit

    click.echo(
        "Warning: `pipeline` is deprecated; use `submit -p`.", err=True
    )
    asyncio.run(
        run_pipeline_submit(
            pipeline_path, source, _parse_maps(map_args),
            stream=stream, split=split, subset=subset, limit=limit,
        )
    )


@cli.command("receive-pipeline", hidden=True)
@click.argument("pipeline_path")
@click.option("--timeout", type=float, default=None)
@click.option("--limit", type=int, default=None)
def receive_pipeline_deprecated(pipeline_path, timeout, limit):
    """[deprecated] Use `receive -p PIPELINE.yaml`."""
    from llmq_tpu.cli.receive import run_pipeline_receive

    click.echo(
        "Warning: `receive-pipeline` is deprecated; use `receive -p`.",
        err=True,
    )
    asyncio.run(run_pipeline_receive(pipeline_path, timeout=timeout, limit=limit))


# ---------------------------------------------------------------------------
# monitoring / ops
# ---------------------------------------------------------------------------


@cli.command()
@click.argument("queue", required=False)
@click.option("-p", "--pipeline", "pipeline_path", default=None, help="Pipeline YAML to visualize")
def status(queue, pipeline_path):
    """Show connection, queue, or pipeline status."""
    from llmq_tpu.cli.monitor import (
        show_connection_status,
        show_pipeline_status,
        show_status,
    )

    if pipeline_path:
        asyncio.run(show_pipeline_status(pipeline_path))
    elif queue:
        asyncio.run(show_status(queue))
    else:
        asyncio.run(show_connection_status())


@cli.command()
@click.argument("queue")
def health(queue):
    """Heuristic health check for a queue (consumers, backlog)."""
    from llmq_tpu.cli.monitor import check_health

    asyncio.run(check_health(queue))


@cli.command()
@click.argument("queue")
@click.option(
    "--limit",
    type=int,
    default=10,
    show_default=True,
    help="Max jobs to list, or to move with --requeue (0 = all)",
)
@click.option(
    "--requeue",
    is_flag=True,
    help="Move the failed jobs back onto the queue for retry "
    "(destructive on <queue>.failed; --limit bounds how many, 0 = all)",
)
def errors(queue, limit, requeue):
    """List dead-lettered jobs from <queue>.failed."""
    from llmq_tpu.cli.monitor import requeue_errors, show_errors

    if requeue:
        asyncio.run(requeue_errors(queue, limit=None if limit == 0 else limit))
    else:
        asyncio.run(show_errors(queue, limit=limit))


@cli.command()
@click.argument("job_id")
@click.option("-q", "--queue", required=True,
              help="Queue the job was submitted to (its .results queue is "
                   "peeked non-destructively)")
def trace(job_id, queue):
    """Show a job's lifecycle timeline (submitted → claimed → prefill →
    first token → finished) from the trace record in its result."""
    from llmq_tpu.cli.monitor import trace_job

    asyncio.run(trace_job(queue, job_id))


@cli.command()
@click.argument("queue")
@click.option("--host", default="127.0.0.1", show_default=True,
              help="Bind address for the HTTP server")
@click.option("--port", type=int, default=None,
              help="Bind port (default: config serve_port / LLMQ_SERVE_PORT; "
                   "0 = ephemeral)")
@click.option("--model-name", default="llmq-tpu", show_default=True,
              help="Model id reported by /v1/models and in responses")
@click.option("--priority", type=click.Choice(["interactive", "batch"]),
              default="interactive", show_default=True,
              help="Default SLO class for requests that don't set one")
def serve(queue, host, port, model_name, priority):
    """Run the OpenAI-compatible HTTP/SSE gateway in front of QUEUE.

    Endpoints: POST /v1/completions, POST /v1/chat/completions
    (stream=true for SSE token deltas), GET /v1/models, GET /healthz.
    Requests default to the interactive SLO class, so they ride the
    fast lane ahead of the batch backlog.
    """
    import time as _time

    from llmq_tpu.gateway import ServingGateway

    gw = ServingGateway(
        queue,
        host=host,
        port=port,
        model_name=model_name,
        default_priority=priority,
    )
    gw.start()
    click.echo(f"Serving {queue} on http://{host}:{gw.port} (Ctrl-C to stop)")
    try:
        while True:
            _time.sleep(1.0)
    except KeyboardInterrupt:
        pass
    finally:
        gw.stop()


@cli.group()
def monitor() -> None:
    """Live observability dashboards."""


@monitor.command("top")
@click.argument("queue")
@click.option("--interval", type=float, default=2.0, show_default=True,
              help="Refresh period in seconds")
@click.option("--once", is_flag=True,
              help="Render one snapshot and exit (scripts/tests)")
@click.option("--top", "top_n", type=int, default=40, show_default=True,
              help="Rows to render: the N busiest workers by occupancy "
                   "(the summary line always covers the whole fleet)")
def monitor_top_cmd(queue, interval, once, top_n):
    """Live fleet dashboard: tok/s, occupancy, TTFT/ITL percentiles,
    reconnects — aggregated from fresh worker heartbeats."""
    from llmq_tpu.cli.monitor import monitor_top

    try:
        asyncio.run(
            monitor_top(
                queue, interval=interval,
                iterations=1 if once else None, top=top_n,
            )
        )
    except KeyboardInterrupt:
        pass


@cli.command()
@click.argument("queue")
@click.option("--yes", is_flag=True, help="Skip confirmation")
def clear(queue, yes):
    """Purge all ready messages from a queue."""
    from llmq_tpu.cli.monitor import clear_queue

    if not yes:
        click.confirm(f"Purge all messages from '{queue}'?", abort=True)
    asyncio.run(clear_queue(queue))


# ---------------------------------------------------------------------------
# static analysis
# ---------------------------------------------------------------------------


@cli.command(
    context_settings={"ignore_unknown_options": True, "help_option_names": []}
)
@click.argument("args", nargs=-1, type=click.UNPROCESSED)
def lint(args):
    """Run the llmq AST lint pass (same as `python -m llmq_tpu.analysis`).

    Checks the async broker/worker/engine invariants: orphan tasks,
    settle exhaustiveness, blocking calls in async code, swallowed
    cancellation, and JAX host syncs. Try `llmq-tpu lint --list-rules`.
    """
    from llmq_tpu.analysis.cli import main as lint_main

    sys.exit(lint_main(list(args)))


# ---------------------------------------------------------------------------
# workers
# ---------------------------------------------------------------------------


@cli.group()
def worker() -> None:
    """Run workers (TPU inference, dummy, dedup, pipeline stages)."""


@worker.command("run")
@click.argument("model")
@click.argument("queue")
@click.option("-tp", "--tensor-parallel", type=int, default=None,
              help="Tensor-parallel degree (default: all local devices)")
@click.option("-dp", "--data-parallel", type=int, default=1, show_default=True,
              help="Data-parallel replicas within this worker")
@click.option("-sp", "--sequence-parallel", type=int, default=1,
              show_default=True,
              help="Context-parallel degree (ring attention over long "
                   "prompts)")
@click.option("-c", "--concurrency", type=int, default=None,
              help="Override prefetch/in-flight job count")
@click.option("--max-num-seqs", type=int, default=None, help="Engine batch slots")
@click.option("--max-model-len", type=int, default=None, help="Context window cap")
@click.option("--dtype", default="bfloat16", show_default=True,
              type=click.Choice(["bfloat16", "float32", "int8", "int4"]),
              help="int8 = weight-only quantization (bf16 compute); "
                   "halves HBM footprint and weight bandwidth. "
                   "int4 = AWQ-style group-quantized layer weights "
                   "(per-group scale+zero, bf16 compute); quarters the "
                   "layer-weight footprint (embed/lm_head stay int8)")
@click.option("--kv-dtype", default=None,
              type=click.Choice(["auto", "bf16", "fp8", "fp8_e5m2"]),
              help="KV cache storage dtype: fp8 (float8_e5m2) halves KV "
                   "bytes — double the page pool, half the decode "
                   "attention bandwidth (vLLM kv-cache-dtype parity). "
                   "Default: the compute dtype (or LLMQ_KV_DTYPE)")
@click.option("--prefill-chunk", type=int, default=None,
              help="Chunked prefill: positions per chunk (any prompt "
                   "length through one executable; decode interleaves "
                   "between chunks). Default: bucketed whole-prompt prefill")
@click.option("--prefix-caching", is_flag=True,
              help="Reuse cached KV for shared prompt prefixes "
                   "(requires --prefill-chunk)")
@click.option("--prefix-host-gb", type=float, default=None,
              help="Host-RAM cold tier for the prefix cache: KV pages "
                   "evicted from the device pool park in host RAM (up to "
                   "this many GiB, LRU) and restore via scatter instead "
                   "of re-prefilling. Requires --prefix-caching. "
                   "Default: LLMQ_PREFIX_HOST_GB or 0 (off)")
@click.option("--decode-block", type=int, default=None,
              help="Fused multi-step decode: device iterations per host "
                   "dispatch (K tokens per round trip; a finished "
                   "sequence wastes at most K-1 device iterations). "
                   "Default: LLMQ_DECODE_BLOCK or 1")
@click.option("--spec-tokens", type=int, default=None,
              help="Lossless speculative decoding: n-gram prompt-lookup "
                   "draft tokens verified per decode step (greedy output "
                   "is bit-identical; sampled distributions stay exact "
                   "via rejection sampling — pays off on workloads that "
                   "copy prompt spans). Default: LLMQ_SPEC_TOKENS or 0")
@click.option("--tp-overlap", default=None,
              type=click.Choice(["off", "on", "auto"]),
              help="Tensor-parallel collective overlap: 'on' replaces "
                   "GSPMD's per-layer all-reduces with chunked ppermute "
                   "rings that hide ICI hops behind matmul chunks; 'auto' "
                   "A/Bs ring-vs-GSPMD on this host's chips. Default: "
                   "LLMQ_TP_OVERLAP or off")
@click.option("--mixed-step", default=None,
              type=click.Choice(["off", "on"]),
              help="Piggyback scheduling: fuse one pending request's "
                   "prefill chunk into each decode dispatch (the "
                   "bandwidth-bound decode step's idle MXU does the "
                   "prefill; greedy outputs unchanged). Requires "
                   "--prefill-chunk. Default: LLMQ_MIXED_STEP or off")
@click.option("--role", default=None,
              type=click.Choice(["unified", "prefill", "decode", "auto"]),
              help="Disaggregated serving role: 'prefill' consumes the "
                   "shared queue, runs the prompt phase only, and hands "
                   "KV off to the decode pool; 'decode' consumes "
                   "<queue>.decode and adopts handed-off requests; "
                   "'auto' switches between the two on fleet queue "
                   "depths (hysteresis via LLMQ_ROLE_DWELL_S and the "
                   "LLMQ_ROLE_SWITCH_LO/HI bands). Default: "
                   "LLMQ_WORKER_ROLE or unified (monolith)")
def worker_run(model, queue, tensor_parallel, data_parallel,
               sequence_parallel, concurrency, max_num_seqs, max_model_len,
               dtype, kv_dtype, prefill_chunk, prefix_caching,
               prefix_host_gb, decode_block, spec_tokens, tp_overlap,
               mixed_step, role):
    """Run a TPU inference worker serving MODEL on QUEUE."""
    from llmq_tpu.cli.worker import run_tpu_worker

    run_tpu_worker(
        model, queue,
        tensor_parallel=tensor_parallel,
        data_parallel=data_parallel,
        sequence_parallel=sequence_parallel,
        concurrency=concurrency,
        max_num_seqs=max_num_seqs,
        max_model_len=max_model_len,
        kv_dtype=kv_dtype,
        dtype=dtype,
        prefill_chunk_size=prefill_chunk,
        enable_prefix_caching=prefix_caching,
        prefix_host_gb=prefix_host_gb,
        decode_block=decode_block,
        spec_tokens=spec_tokens,
        tp_overlap=tp_overlap,
        mixed_step=mixed_step,
        role=role,
    )


@worker.command("dummy")
@click.argument("queue")
@click.option("-c", "--concurrency", type=int, default=None)
@click.option("--delay", type=float, default=1.0, show_default=True,
              help="Simulated processing seconds per job")
def worker_dummy(queue, concurrency, delay):
    """Run a dummy echo worker (testing)."""
    from llmq_tpu.cli.worker import run_dummy_worker

    run_dummy_worker(queue, concurrency=concurrency, delay=delay)


@worker.command("dedup")
@click.argument("queue")
@click.option("--batch-size", type=int, default=256, show_default=True)
@click.option("--mode", type=click.Choice(["dedup", "outliers", "representative"]),
              default="dedup", show_default=True)
@click.option("--threshold", type=float, default=0.9, show_default=True,
              help="Similarity threshold for duplicate detection")
@click.option("--embedding", type=click.Choice(["lexical", "model"]),
              default="lexical", show_default=True,
              help="Similarity backend: lexical n-grams, or a model's "
                   "embedding table (catches paraphrases; needs --model)")
@click.option("--model", default=None,
              help="Local HF checkpoint dir for --embedding model")
def worker_dedup(queue, batch_size, mode, threshold, embedding, model):
    """Run a semantic dedup/filter worker (reference: semhash worker)."""
    from llmq_tpu.cli.worker import run_dedup_worker

    run_dedup_worker(queue, batch_size=batch_size, mode=mode,
                     threshold=threshold, embedding=embedding, model=model)


@worker.command("pipeline")
@click.argument("config_path")
@click.argument("stage")
@click.option("-c", "--concurrency", type=int, default=None)
def worker_pipeline(config_path, stage, concurrency):
    """Run a worker for one STAGE of a pipeline YAML."""
    from llmq_tpu.cli.worker import run_pipeline_worker

    run_pipeline_worker(config_path, stage, concurrency=concurrency)


# ---------------------------------------------------------------------------
# broker daemon (llmq-tpu-only: the reference assumed external RabbitMQ)
# ---------------------------------------------------------------------------


@cli.group()
def broker() -> None:
    """Run/inspect the self-hosted broker daemon."""


@broker.command("serve")
@click.option("--host", default="0.0.0.0", show_default=True)
@click.option("--port", type=int, default=5672, show_default=True)
@click.option("--persist-dir", default=None,
              help="Journal directory for durability across restarts")
@click.option("--native/--no-native", default=False, show_default=True,
              help="Exec the C++ daemon (native/broker; wire- and "
                   "journal-compatible) instead of the asyncio one")
def broker_serve(host: str, port: int, persist_dir: Optional[str],
                 native: bool):
    """Start the llmq-tpu broker daemon (the RabbitMQ equivalent)."""
    if native:
        from llmq_tpu.broker.native import ensure_brokerd

        binary = ensure_brokerd()
        if binary is None:
            click.echo("native brokerd not found and build failed "
                       "(need g++/make + the native/ source tree)", err=True)
            sys.exit(1)
        argv = [str(binary), "--host", host, "--port", str(port)]
        if persist_dir:
            argv += ["--persist-dir", persist_dir]
        os.execv(str(binary), argv)

    from llmq_tpu.broker.tcp import BrokerServer
    from llmq_tpu.utils.logging import setup_logging

    setup_logging(structured=False)
    server = BrokerServer(host, port, persist_dir=persist_dir)
    click.echo(f"llmq-tpu broker daemon on {host}:{port}"
               + (f" (journal: {persist_dir})" if persist_dir else " (in-memory)"))
    try:
        asyncio.run(server.serve_forever())
    except KeyboardInterrupt:
        click.echo("broker stopped")


# ---------------------------------------------------------------------------
# fleet simulation (virtual-clock discrete-event twin)
# ---------------------------------------------------------------------------


@cli.group()
def sim() -> None:
    """Fleet twin: deterministic virtual-clock simulation of the worker
    control plane with invariant checking and policy regressions."""


@sim.command("run")
@click.argument("name", required=False)
@click.option("--file", "file_", default=None,
              help="Load the scenario from a JSON file instead of NAME")
@click.option("--seed", type=int, default=None, help="Override scenario seed")
@click.option("--json", "as_json", is_flag=True,
              help="Emit the full report summary as JSON")
def sim_run_cmd(name, file_, seed, as_json):
    """Run a scenario and check invariants (exit 1 on violations)."""
    from llmq_tpu.cli.sim import sim_run

    sim_run(name, file_, seed, as_json)


@sim.command("replay")
@click.argument("name", required=False)
@click.option("--file", "file_", default=None,
              help="Load the scenario from a JSON file instead of NAME")
@click.option("--seed", type=int, default=None, help="Override scenario seed")
def sim_replay_cmd(name, file_, seed):
    """Run a scenario twice; exit 1 unless the event streams are
    digest-identical (determinism proof)."""
    from llmq_tpu.cli.sim import sim_replay

    sim_replay(name, file_, seed)


@sim.command("regress")
@click.argument("name", required=False)
@click.option("--detuned", is_flag=True,
              help="Prove teeth: run with the documented detune and "
                   "require the baseline bounds to BREAK")
def sim_regress_cmd(name, detuned):
    """Run the policy regression suite against recorded baselines."""
    from llmq_tpu.cli.sim import sim_regress

    sim_regress(name, detuned)


@sim.command("list")
def sim_list_cmd():
    """List named scenarios with their documented detunes."""
    from llmq_tpu.cli.sim import sim_list

    sim_list()


def main() -> None:  # console-script entry point
    cli()


if __name__ == "__main__":
    main()
