"""fp8 (float8_e5m2) KV cache: kernels, writes, and the engine.

The reference's engine inherits quantized KV caches from vLLM
(``kv-cache-dtype=fp8`` — scale-free e5m2 storage); here the page pools
simply allocate as ``float8_e5m2``: writes cast on store, every reader
(XLA references and the Pallas kernels, which already convert pages to
f32 on-chip) dequantizes on load. Half the KV bytes — double the page
pool in the same HBM, half the decode-attention bandwidth.

Test strategy: fp8 quantization is deterministic, so the Pallas kernels
are compared against the XLA references over the SAME fp8 pool at tight
tolerance (both dequantize identical bits); engine-level runs assert
completion + determinism, not cross-dtype token equality (rounding can
legitimately flip a greedy pick on random tiny models).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llmq_tpu.engine.engine import EngineConfig, EngineCore
from llmq_tpu.engine.sampling import SamplingParams
from llmq_tpu.engine.tokenizer import ByteTokenizer
from llmq_tpu.models.config import ModelConfig
from llmq_tpu.models.transformer import init_params
from llmq_tpu.ops import attention as ref_ops
from llmq_tpu.ops import pallas_attention as pk
from llmq_tpu.ops.dispatch import _WINDOW_DISABLED

pytestmark = pytest.mark.unit

FP8 = jnp.float8_e5m2


def _rand(key, shape):
    return jax.random.normal(key, shape, jnp.float32) * 0.3


def _fp8_paged_setup(key, *, S, n_kv, d, page_size, pages_per_seq, ctx_lens,
                     layers=None):
    P = 1 + S * pages_per_seq
    shape = (P, page_size, n_kv, d) if layers is None else (
        layers, P, page_size, n_kv, d
    )
    k1, k2 = jax.random.split(key)
    k_pages = _rand(k1, shape).astype(FP8)
    v_pages = _rand(k2, shape).astype(FP8)
    bt = jnp.arange(1, 1 + S * pages_per_seq, dtype=jnp.int32).reshape(S, -1)
    return k_pages, v_pages, bt, jnp.asarray(ctx_lens, jnp.int32)


class TestFp8XlaPaths:
    def test_paged_decode_matches_dequantized_pool(self):
        """The XLA reference over an fp8 pool equals the same reference
        over the pre-dequantized pool — the cast happens on load, before
        any arithmetic."""
        S, n_heads, n_kv, d, page_size, pps = 3, 4, 2, 16, 8, 3
        q = _rand(jax.random.key(0), (S, n_heads, d))
        kp, vp, bt, cl = _fp8_paged_setup(
            jax.random.key(1), S=S, n_kv=n_kv, d=d, page_size=page_size,
            pages_per_seq=pps, ctx_lens=[1, 9, 24],
        )
        out = ref_ops.paged_decode_attention(
            q, kp, vp, bt, cl, scale=d**-0.5
        )
        ref = ref_ops.paged_decode_attention(
            q, kp.astype(jnp.float32), vp.astype(jnp.float32), bt, cl,
            scale=d**-0.5,
        )
        np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-6)

    def test_writes_cast_to_pool_dtype(self):
        """Both write paths store fp8 when the pool is fp8, matching an
        explicit host-side cast."""
        S, n_kv, d, page_size, pps, L = 2, 2, 16, 8, 2, 2
        P = 1 + S * pps
        kp = jnp.zeros((L, P, page_size, n_kv, d), FP8)
        vp = jnp.zeros((L, P, page_size, n_kv, d), FP8)
        bt = jnp.arange(1, 1 + S * pps, dtype=jnp.int32).reshape(S, -1)
        li = jnp.asarray(0, jnp.int32)

        kn = _rand(jax.random.key(2), (S, 1, n_kv, d))
        vn = _rand(jax.random.key(3), (S, 1, n_kv, d))
        positions = jnp.asarray([[3], [7]], jnp.int32)
        kp2, vp2 = ref_ops.write_kv_pages(kp, vp, kn, vn, bt, positions, li)
        assert kp2.dtype == FP8 and vp2.dtype == FP8
        got = kp2[0, bt[1, 0], 7].astype(jnp.float32)
        np.testing.assert_array_equal(
            got, kn[1, 0].astype(FP8).astype(jnp.float32)
        )

        T = page_size * pps
        kb = _rand(jax.random.key(4), (S, T, n_kv, d))
        vb = _rand(jax.random.key(5), (S, T, n_kv, d))
        kp3, vp3 = ref_ops.write_prompt_kv_pages(kp, vp, kb, vb, bt, li)
        assert kp3.dtype == FP8
        np.testing.assert_array_equal(
            kp3[0, bt[0, 0]].astype(jnp.float32),
            kb[0, :page_size].astype(FP8).astype(jnp.float32),
        )


class TestFp8PallasKernels:
    @pytest.mark.parametrize(
        "kernel",
        [pk.paged_decode_attention_pallas, pk.paged_decode_attention_pallas_v2],
        ids=["v1", "v2"],
    )
    def test_decode_kernels_match_reference_on_fp8_pool(self, kernel):
        S, n_heads, n_kv, d, page_size, pps = 4, 8, 2, 16, 8, 4
        ctx = [1, 8, 19, 32]
        q = _rand(jax.random.key(6), (S, n_heads, d))
        kp, vp, bt, cl = _fp8_paged_setup(
            jax.random.key(7), S=S, n_kv=n_kv, d=d, page_size=page_size,
            pages_per_seq=pps, ctx_lens=ctx,
        )
        ref = ref_ops.paged_decode_attention(q, kp, vp, bt, cl, scale=d**-0.5)
        out = kernel(
            q, kp, vp, bt, cl,
            jnp.asarray([_WINDOW_DISABLED], jnp.int32),
            scale=d**-0.5, interpret=True,
        )
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

    def test_v3_fused_write_fp8_pool(self):
        """v3 stores this step's rows INTO the fp8 pool in-kernel; pool
        and output must match scatter-then-decode over the same dtypes."""
        S, n_heads, n_kv, d, page_size, pps, L = 3, 4, 2, 16, 8, 3, 2
        ctx = [1, 9, 0]
        q = _rand(jax.random.key(8), (S, n_heads, d))
        kp, vp, bt, cl = _fp8_paged_setup(
            jax.random.key(9), S=S, n_kv=n_kv, d=d, page_size=page_size,
            pages_per_seq=pps, ctx_lens=ctx, layers=L,
        )
        kn = _rand(jax.random.key(10), (S, n_kv, d))
        vn = _rand(jax.random.key(11), (S, n_kv, d))
        li = jnp.asarray(1, jnp.int32)
        win = jnp.asarray([_WINDOW_DISABLED], jnp.int32)
        positions = jnp.where(cl > 0, cl - 1, -1)[:, None]
        kp_ref, vp_ref = ref_ops.write_kv_pages(
            kp, vp, kn[:, None], vn[:, None], bt, positions, layer=li
        )
        ref = ref_ops.paged_decode_attention(
            q, kp_ref, vp_ref, bt, cl, scale=d**-0.5, layer=li
        )
        out, kp3, vp3 = pk.paged_decode_attention_pallas_v3(
            q, kp, vp, kn, vn, bt, cl, win, li,
            scale=d**-0.5, interpret=True,
        )
        assert kp3.dtype == FP8
        active = np.asarray([r for r in range(S) if ctx[r] > 0])
        np.testing.assert_allclose(
            np.asarray(out)[active], np.asarray(ref)[active],
            rtol=2e-5, atol=2e-5,
        )
        np.testing.assert_array_equal(
            kp3[:, 1:].astype(jnp.float32), kp_ref[:, 1:].astype(jnp.float32)
        )
        np.testing.assert_array_equal(
            vp3[:, 1:].astype(jnp.float32), vp_ref[:, 1:].astype(jnp.float32)
        )

    @pytest.mark.parametrize("q_dtype", [jnp.float32, jnp.bfloat16])
    def test_chunked_prefill_kernel_fp8_pool(self, q_dtype):
        """fp8 pages upcast to the query dtype inside the kernel — both
        the f32 (tests) and bf16 (production MXU full-rate) paths."""
        B, C, n_heads, n_kv, d, page_size, pps = 2, 8, 4, 2, 16, 8, 3
        q = _rand(jax.random.key(12), (B, C, n_heads, d)).astype(q_dtype)
        kp, vp, bt, _ = _fp8_paged_setup(
            jax.random.key(13), S=B, n_kv=n_kv, d=d, page_size=page_size,
            pages_per_seq=pps, ctx_lens=[0] * B,
        )
        # Row 0: positions 4..11; row 1: 0..5 then padding.
        q_positions = jnp.asarray(
            [[4, 5, 6, 7, 8, 9, 10, 11], [0, 1, 2, 3, 4, 5, -1, -1]],
            jnp.int32,
        )
        ref = ref_ops.paged_prefill_attention(
            q, kp, vp, bt, q_positions, scale=d**-0.5
        )
        num_valid = (q_positions >= 0).sum(axis=1).astype(jnp.int32)
        chunk_start = jnp.where(num_valid > 0, q_positions[:, 0], 0)
        out = pk.paged_prefill_attention_pallas(
            q, kp, vp, bt, chunk_start, num_valid,
            jnp.asarray([_WINDOW_DISABLED], jnp.int32),
            jnp.zeros((1,), jnp.int32),
            scale=d**-0.5, interpret=True,
        )
        valid = np.asarray(q_positions) >= 0
        tol = 2e-5 if q_dtype == jnp.float32 else 3e-2
        np.testing.assert_allclose(
            np.asarray(out, np.float32)[valid],
            np.asarray(ref, np.float32)[valid],
            rtol=tol, atol=tol,
        )


CFG = ModelConfig.tiny(
    vocab_size=128,
    hidden_size=64,
    num_layers=2,
    num_heads=4,
    num_kv_heads=2,
    intermediate_size=128,
    model_type="qwen2",
)


def _run_engine(kv_dtype, *, chunked=False):
    params = init_params(CFG, jax.random.key(0), dtype=jnp.float32)
    core = EngineCore(
        CFG,
        params,
        ByteTokenizer(),
        engine_config=EngineConfig(
            max_num_seqs=2,
            max_model_len=64,
            page_size=8,
            num_pages=32,
            kv_dtype=kv_dtype,
            min_prefill_bucket=16,
            prefill_chunk_size=8 if chunked else None,
        ),
    )
    for i in range(3):
        core.add_request(
            f"r{i}",
            prompt=f"fp8 kv cache request {i}",
            params=SamplingParams(temperature=0.0, max_tokens=8,
                                  ignore_eos=True),
        )
    finished = {}
    for _ in range(200):
        for out in core.step():
            finished[out.rid] = out
        if not core.has_work:
            break
    assert sorted(finished) == ["r0", "r1", "r2"]
    assert all(f.completion_tokens == 8 for f in finished.values())
    return {rid: f.token_ids for rid, f in finished.items()}


class TestFp8Engine:
    def test_config_resolves_strings(self):
        assert EngineConfig(kv_dtype="fp8").kv_dtype == FP8
        assert EngineConfig(kv_dtype="fp8_e5m2").kv_dtype == FP8
        assert EngineConfig(kv_dtype="bf16").kv_dtype == jnp.bfloat16
        assert EngineConfig(kv_dtype="float32").kv_dtype == jnp.float32
        with pytest.raises(ValueError, match="kv_dtype"):
            EngineConfig(kv_dtype="int4")

    def test_fp8_engine_deterministic_end_to_end(self):
        a = _run_engine("fp8")
        b = _run_engine("fp8")
        assert a == b  # fp8 rounding is deterministic

    def test_fp8_engine_chunked_prefill(self):
        a = _run_engine("fp8", chunked=True)
        assert all(len(t) == 8 for t in a.values())

    def test_fp8_pool_halves_bytes(self):
        params = init_params(CFG, jax.random.key(0), dtype=jnp.float32)
        cores = {
            name: EngineCore(
                CFG, params, ByteTokenizer(),
                engine_config=EngineConfig(
                    max_num_seqs=2, max_model_len=64, page_size=8,
                    num_pages=32, kv_dtype=name,
                ),
            )
            for name in ("bf16", "fp8")
        }
        nbytes = {
            name: core.k_pages.nbytes for name, core in cores.items()
        }
        assert nbytes["fp8"] * 2 == nbytes["bf16"]
