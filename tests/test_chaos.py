"""Deterministic fault injection: a worker under ChaosBroker connection
kills must lose no results and never exit.

Everything here runs on CPU against the in-process memory core; the chaos
decorator (seeded RNG + op counter) makes each run replay identically.
The plain ``memory://<ns>`` side of each test shares the namespace with
the ``chaos+memory://<ns>`` side, so submission and result collection see
the same queues without experiencing the injected faults themselves.
"""

import asyncio
import json

import pytest

from llmq_tpu.broker.chaos import ChaosBroker
from llmq_tpu.broker.manager import BrokerManager
from llmq_tpu.core.config import Config
from llmq_tpu.core.models import Job
from llmq_tpu.workers.dummy import DummyWorker

pytestmark = pytest.mark.chaos


def _chaos_cfg(mem_ns: str, **params) -> Config:
    query = "&".join(f"{k}={v}" for k, v in params.items())
    return Config(
        broker_url=f"chaos+memory://{mem_ns}?{query}",
        # Kill-induced requeues bump delivery counts; the cap must not
        # dead-letter jobs whose only sin was a chaotic connection.
        max_redeliveries=1000,
        reconnect_base_delay_s=0.01,
        reconnect_max_delay_s=0.05,
    )


async def _collect_unique_results(mgr, queue, want, timeout=60.0):
    """Drain result ids, deduping: redelivery after a kill may produce a
    second result for the same job (at-least-once), which is allowed."""
    ids = set()
    deadline = asyncio.get_running_loop().time() + timeout
    while len(ids) < want:
        assert asyncio.get_running_loop().time() < deadline, (
            f"only {len(ids)}/{want} results arrived"
        )
        msg = await mgr.broker.get(queue)
        if msg is None:
            await asyncio.sleep(0.02)
            continue
        ids.add(json.loads(msg.body)["id"])
        await msg.ack()
    return ids


class TestChaosWorker:
    async def test_worker_survives_repeated_connection_kills(self, mem_ns):
        """Acceptance: 200 jobs through a worker whose broker connection
        dies every 37th operation — zero lost results, worker never exits,
        reconnects observed."""
        chaos_cfg = _chaos_cfg(mem_ns, kill_every=37, seed=11)
        plain_cfg = Config(broker_url=f"memory://{mem_ns}", max_redeliveries=1000)
        async with BrokerManager(plain_cfg) as mgr:
            await mgr.setup_queue_infrastructure("cq")
            for i in range(200):
                await mgr.publish_job("cq", Job(id=f"c{i}", prompt=f"p{i}"))

            worker = DummyWorker("cq", delay=0, config=chaos_cfg, concurrency=8)
            task = asyncio.ensure_future(worker.run())
            try:
                ids = await _collect_unique_results(mgr, "cq.results", 200)
                assert ids == {f"c{i}" for i in range(200)}
                assert not task.done(), "worker exited under chaos"
                stats = worker.broker.session_stats
                assert stats is not None and stats.reconnects > 0
                kills = worker.broker.broker.inner.kills
                assert kills > 0
            finally:
                worker.request_shutdown()
                await asyncio.wait_for(task, timeout=30.0)

    async def test_duplicate_deliveries_reach_handler(self, mem_ns):
        """dup_every re-invokes the consumer handler with a settle-less
        copy — the consumer-side idempotency surface."""
        feeder = BrokerManager(Config(broker_url=f"memory://{mem_ns}"))
        await feeder.connect()
        await feeder.broker.declare_queue("dq")

        chaos = ChaosBroker(f"chaos+memory://{mem_ns}?dup_every=3&seed=5")
        await chaos.connect()
        seen: list[str] = []

        async def handler(msg):
            seen.append(msg.message_id)
            await msg.ack()

        await chaos.consume("dq", handler, prefetch=10)
        for i in range(6):
            await feeder.broker.publish("dq", b"x", message_id=f"d{i}")

        deadline = asyncio.get_running_loop().time() + 10.0
        while len(seen) < 8:
            assert asyncio.get_running_loop().time() < deadline
            await asyncio.sleep(0.02)
        # 6 deliveries + every 3rd duplicated = 8 handler invocations.
        assert len(seen) == 8
        assert chaos.duplicates == 2
        # Duplicates repeat ids already seen; the set stays exact.
        assert set(seen) == {f"d{i}" for i in range(6)}
        # The dup's settle was a no-op: nothing stuck unacked.
        assert (await feeder.broker.stats("dq")).message_count == 0
        await chaos.close()
        await feeder.disconnect()

    async def test_chaos_runs_are_deterministic(self, mem_ns):
        """Same seed + same op sequence → kills land on the same ops."""

        async def run(ns):
            b = ChaosBroker(f"chaos+memory://{ns}?kill_every=4&seed=42")
            await b.connect()
            killed_at = []
            for i in range(10):
                try:
                    await b.publish("q", b"x", message_id=f"m{i}")
                except ConnectionError:
                    killed_at.append(i)
                    await b.connect()  # re-dial, as the session layer would
            await b.close()
            return killed_at

        first = await run(f"{mem_ns}-a")
        second = await run(f"{mem_ns}-b")
        assert first == second
        assert first, "kill_every=4 over 10 publishes must kill at least once"


@pytest.mark.slow
class TestChaosSoak:
    async def test_long_soak_with_kills_dups_and_delays(self, mem_ns):
        chaos_cfg = _chaos_cfg(
            mem_ns, kill_every=17, dup_every=29, delay_ms=2, seed=7
        )
        plain_cfg = Config(broker_url=f"memory://{mem_ns}", max_redeliveries=1000)
        async with BrokerManager(plain_cfg) as mgr:
            await mgr.setup_queue_infrastructure("sq")
            for i in range(500):
                await mgr.publish_job("sq", Job(id=f"s{i}", prompt=f"p{i}"))
            worker = DummyWorker("sq", delay=0, config=chaos_cfg, concurrency=8)
            task = asyncio.ensure_future(worker.run())
            try:
                ids = await _collect_unique_results(
                    mgr, "sq.results", 500, timeout=240.0
                )
                assert ids == {f"s{i}" for i in range(500)}
                assert not task.done()
                stats = worker.broker.session_stats
                assert stats is not None and stats.reconnects > 0
            finally:
                worker.request_shutdown()
                await asyncio.wait_for(task, timeout=30.0)


class TestChaosTrace:
    async def test_trace_survives_redelivery(self, mem_ns):
        """A job whose first processing attempt fails is redelivered; its
        result must still carry the lifecycle trace, with ``redeliveries``
        counting the failed attempt and NO duplicated lifecycle events —
        the redelivered message re-reads the original payload, so the
        failed attempt's events never stack."""
        from llmq_tpu.obs import trace_from_payload

        plain_cfg = Config(
            broker_url=f"memory://{mem_ns}", max_redeliveries=1000
        )

        class FlakyWorker(DummyWorker):
            def __init__(self, *args, **kwargs):
                super().__init__(*args, **kwargs)
                self.attempts = 0

            async def _process_job(self, job):
                self.attempts += 1
                if self.attempts == 1:
                    raise RuntimeError("injected first-attempt failure")
                return await super()._process_job(job)

        async with BrokerManager(plain_cfg) as mgr:
            await mgr.setup_queue_infrastructure("trq")
            await mgr.publish_job("trq", Job(id="t0", prompt="hello"))
            worker = FlakyWorker("trq", delay=0, config=plain_cfg)
            task = asyncio.ensure_future(worker.run())
            try:
                payload = None
                deadline = asyncio.get_running_loop().time() + 30.0
                while payload is None:
                    assert asyncio.get_running_loop().time() < deadline, (
                        "result never arrived after redelivery"
                    )
                    msg = await mgr.broker.get("trq.results")
                    if msg is None:
                        await asyncio.sleep(0.02)
                        continue
                    payload = json.loads(msg.body)
                    await msg.ack()
            finally:
                worker.request_shutdown()
                await asyncio.wait_for(task, timeout=30.0)

        assert worker.attempts == 2
        trace = trace_from_payload(payload)
        assert trace is not None, "result lost its trace across redelivery"
        assert trace["redeliveries"] == 1
        names = [e["name"] for e in trace["events"]]
        # Exactly one of each lifecycle event: the failed first attempt's
        # claim was stamped on a copy that died with the requeue.
        assert names == ["submitted", "claimed", "finished"]
        claimed = next(e for e in trace["events"] if e["name"] == "claimed")
        assert claimed["delivery_count"] == 1
        walls = [e["t_wall"] for e in trace["events"]]
        assert walls == sorted(walls)
