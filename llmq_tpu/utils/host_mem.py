"""Unified host-memory governor.

Three subsystems buffer KV-derived bytes in host RAM — the prefix cold
tier (``engine/prefix_store.py``), snapshot swap-preemption
(``engine/engine.py`` capture paths), and resume-republish blobs
(``workers/base.py`` handoff) — and before this module each sized itself
independently, so their budgets only composed by luck: a worker tuned
for a 4 GiB prefix tier plus a burst of swap-preempts could overshoot
container RAM and get OOM-killed, taking every in-flight request with it.

:class:`HostMemoryGovernor` gives them one shared byte budget
(``LLMQ_HOST_MEM_GB``) with an explicit degradation ladder — each rung
trades throughput, never correctness:

1. **Evict cold prefixes.** Prefix pages are a pure cache; dropping one
   costs a re-prefill at worst.
2. **Refuse swap-preempt** (above ``SWAP_REFUSE_FRAC`` of budget, after
   eviction). The engine falls back to recompute-preemption — the
   pre-PR-8 behavior, always correct, just slower on resume.
3. **Refuse KV-ship serves** (above ``SERVE_REFUSE_FRAC``). Peers
   recompute locally instead of pulling pages; export buffers are the
   last optional allocation standing.

Resume-republish blobs are *accounted but never refused* — refusing them
would strand an in-flight request during drain, which is exactly the
moment the handoff path must not fail.

A budget of 0 (the default) disables the governor entirely: every
``admit_*`` answers yes and no eviction pressure is applied, so existing
deployments see no behavior change until they opt in.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

# Degradation-ladder thresholds, as fractions of the byte budget. Swap
# refuses before serve so that rising pressure sheds optional *local*
# buffering before it stops helping *remote* peers — by the time serves
# are refused the host is nearly full and export buffers are the only
# allocation left to cut.
SWAP_REFUSE_FRAC = 0.85
SERVE_REFUSE_FRAC = 0.95


class HostMemoryGovernor:
    """One shared byte budget across host-RAM consumers.

    Consumers ``register(name, usage_fn, evict_fn=None)`` — ``usage_fn``
    reports their current bytes, ``evict_fn(nbytes)`` (optional) frees at
    least-effort toward ``nbytes`` and returns bytes actually freed.
    Admission checks then see the *global* occupancy, not one store's.
    """

    def __init__(self, budget_bytes: int) -> None:
        self.budget_bytes = max(0, int(budget_bytes))
        self._lock = threading.Lock()
        self._usage_fns: Dict[str, Callable[[], int]] = {}
        self._evict_fns: Dict[str, Callable[[int], int]] = {}
        # Degradation/pressure counters (surfaced via stats()/heartbeats).
        self.evictions_forced = 0
        self.swap_refusals = 0
        self.serve_refusals = 0

    @property
    def enabled(self) -> bool:
        return self.budget_bytes > 0

    def register(
        self,
        name: str,
        usage_fn: Callable[[], int],
        evict_fn: Optional[Callable[[int], int]] = None,
    ) -> None:
        """Register (or replace) a consumer. Idempotent per name so
        engine restarts inside one process re-bind cleanly."""
        with self._lock:
            self._usage_fns[name] = usage_fn
            if evict_fn is not None:
                self._evict_fns[name] = evict_fn
            else:
                self._evict_fns.pop(name, None)

    def unregister(self, name: str) -> None:
        with self._lock:
            self._usage_fns.pop(name, None)
            self._evict_fns.pop(name, None)

    def usage_bytes(self) -> int:
        """Sum of all registered consumers' current bytes (0 on any
        consumer error — a broken gauge must not wedge admission)."""
        with self._lock:
            fns = list(self._usage_fns.values())
        total = 0
        for fn in fns:
            try:
                total += max(0, int(fn()))
            except Exception:  # noqa: BLE001 — gauges are best-effort
                pass
        return total

    def _evict_toward(self, target_bytes: int) -> int:
        """Ladder rung 1: ask evictors (cold prefixes first — they are
        the only registered evictors today) to free until global usage
        fits under ``target_bytes``. Returns bytes freed."""
        freed = 0
        with self._lock:
            evictors = list(self._evict_fns.values())
        for evict in evictors:
            over = self.usage_bytes() - target_bytes
            if over <= 0:
                break
            try:
                got = int(evict(over))
            except Exception:  # noqa: BLE001
                got = 0
            if got > 0:
                freed += got
                self.evictions_forced += 1
        return freed

    def admit_swap(self, nbytes: int) -> bool:
        """May the engine buffer a swap-preempt capture of ``nbytes``?

        Tries prefix eviction first; refuses only if even after eviction
        the capture would push usage past ``SWAP_REFUSE_FRAC`` of budget.
        A refusal is safe — the caller falls back to recompute-preemption.
        """
        if not self.enabled:
            return True
        limit = int(self.budget_bytes * SWAP_REFUSE_FRAC)
        if self.usage_bytes() + nbytes <= limit:
            return True
        self._evict_toward(limit - nbytes)
        if self.usage_bytes() + nbytes <= limit:
            return True
        self.swap_refusals += 1
        return False

    def admit_serve(self) -> bool:
        """May this worker build an export buffer to serve a KV-ship
        fetch? Refused only near the top of the budget (the final rung);
        the peer recomputes, which is always correct."""
        if not self.enabled:
            return True
        if self.usage_bytes() <= int(self.budget_bytes * SERVE_REFUSE_FRAC):
            return True
        self.serve_refusals += 1
        return False

    def note_resume_blob(self, nbytes: int) -> None:
        """Account a resume-republish blob. Never refuses (refusal would
        strand an in-flight request mid-drain) but applies eviction
        pressure so the *next* optional allocation sees the cost."""
        if not self.enabled or nbytes <= 0:
            return
        if self.usage_bytes() + nbytes > self.budget_bytes:
            self._evict_toward(self.budget_bytes - nbytes)

    def stats(self) -> Dict[str, int]:
        return {
            "budget_bytes": self.budget_bytes,
            "usage_bytes": self.usage_bytes(),
            "evictions_forced": self.evictions_forced,
            "swap_refusals": self.swap_refusals,
            "serve_refusals": self.serve_refusals,
        }


_governor: Optional[HostMemoryGovernor] = None
_governor_lock = threading.Lock()


def get_governor() -> HostMemoryGovernor:
    """Process-wide governor, sized from ``LLMQ_HOST_MEM_GB`` on first
    use (0/unset = disabled — all admissions pass)."""
    global _governor
    with _governor_lock:
        if _governor is None:
            from llmq_tpu.core.config import get_config

            gb = get_config().host_mem_gb or 0.0
            _governor = HostMemoryGovernor(int(gb * (1 << 30)))
        return _governor


def set_governor(governor: Optional[HostMemoryGovernor]) -> None:
    """Swap the process governor (tests / probes re-size budgets)."""
    global _governor
    with _governor_lock:
        _governor = governor
