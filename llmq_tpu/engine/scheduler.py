"""Continuous-batching scheduler: slots, paged KV allocation, preemption.

This is the host-side half of what vLLM's C++/CUDA scheduler did for the
reference (SURVEY.md §2b "continuous batching scheduler"). The device half
is a *fixed-shape* compiled decode step over ``max_num_seqs`` slots; this
module decides which sequence lives in which slot and which physical KV
pages back it, so the device program never recompiles as requests churn.

Invariants (property-tested in tests/test_scheduler.py):
  - every physical page's refcount equals the number of running sequences
    listing it (exactly one owner unless prefix caching shares it; page 0
    is a reserved scratch page and is never handed out),
  - every admitted sequence has pages covering len(tokens)+1 positions
    (room for the KV write of the token being decoded),
  - slots hold at most one sequence; finished/preempted sequences release
    their references immediately (cache-registered pages park in an
    evictable LRU pool instead of the free list),
  - admission is FIFO (priority-aware schedulers admit interactive
    waiters first, FIFO within each class); preemption evicts the
    *youngest* running sequence (its re-prefill wastes the least work;
    priority-aware schedulers prefer batch victims).
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

from llmq_tpu.engine.sampling import SamplingParams
from llmq_tpu.obs.metrics import Histogram
from llmq_tpu.utils.hashing import token_prefix_chain


class OutOfPages(Exception):
    """No free KV pages; caller should preempt or defer."""


def _ms(seconds: Optional[float]) -> Optional[float]:
    """Histogram percentile (seconds) → rounded ms for stats dicts."""
    return None if seconds is None else round(seconds * 1000.0, 3)


def mixed_token_budget(
    chunk_size: int, decode_rows: int, remaining: int, *, min_tokens: int = 1
) -> int:
    """Prefill positions one piggybacked chunk segment may claim in a
    mixed (decode + prefill) dispatch iteration.

    The per-iteration token budget is ``chunk_size`` (the fused
    executable's fixed chunk width): each decodable row consumes one
    budget token for its own decode position, and the head-of-line
    prefill gets the remainder. A busy batch therefore trickles the
    prompt in small segments (the decode rows' latency is protected),
    while an idle batch prefills at full chunk width. ``min_tokens``
    floors the segment so prefill always makes progress even when
    decode_rows >= chunk_size; the segment can never exceed the chunk
    row's physical width (``chunk_size``) or the prompt's ``remaining``
    positions. Returns 0 when nothing remains."""
    if remaining <= 0:
        return 0
    return min(remaining, max(chunk_size - decode_rows, min_tokens), chunk_size)


class PageAllocator:
    """Refcounted free-list allocator over the physical KV page pool.

    Page 0 is reserved: masked/padded token positions scatter there
    (``ops/attention.py::write_kv_pages``), so it must never back live data.

    Three page states:
      - *allocated*: refcount ≥ 1 (prefix-cached pages shared by several
        sequences carry one reference per sharer);
      - *cached*: refcount dropped to 0 but the page was registered as
        evictable (its KV content may be reused by a future prefix
        match) — it is reclaimed lazily, LRU, under pool pressure;
      - *free*: on the free list.
    Without prefix caching every page has refcount 1 and the allocator
    degenerates to the plain free list.
    """

    def __init__(self, num_pages: int) -> None:
        if num_pages < 2:
            raise ValueError("need at least 2 pages (page 0 is reserved)")
        self.num_pages = num_pages
        self._free: List[int] = list(range(num_pages - 1, 0, -1))
        self._refs: Dict[int, int] = {}
        # LRU order of refcount-0 evictable pages (dict = ordered set).
        self._cached: Dict[int, None] = {}
        # Called with the page id when a cached page is evicted, so the
        # prefix cache can drop entries pointing at it.
        self.on_evict = None

    @property
    def available(self) -> int:
        return len(self._free) + len(self._cached)

    def refcount(self, page: int) -> int:
        return self._refs.get(page, 0)

    def alloc(self, n: int = 1) -> List[int]:
        """Allocate n fresh pages atomically; raises OutOfPages if short
        (evicting cached pages as needed, oldest first)."""
        if n > self.available:
            raise OutOfPages(f"want {n} pages, have {self.available}")
        while len(self._free) < n:
            self._evict_one()
        pages = [self._free.pop() for _ in range(n)]
        for page in pages:
            self._refs[page] = 1
        return pages

    def share(self, page: int) -> None:
        """Take an additional reference on an allocated or cached page."""
        rc = self._refs.get(page)
        if rc is None:
            raise ValueError(f"share of unallocated page {page}")
        if rc == 0:  # revive from the evictable pool
            del self._cached[page]
        self._refs[page] = rc + 1

    def free(self, pages: List[int], *, cacheable: bool = False) -> None:
        """Drop one reference per page. At refcount 0 the page returns to
        the free list — or parks in the evictable LRU pool when
        ``cacheable`` (its content may serve a future prefix match)."""
        for page in pages:
            rc = self._refs.get(page)
            if rc is None or rc < 1:
                raise ValueError(f"double-free or foreign page {page}")
            if rc > 1:
                self._refs[page] = rc - 1
                continue
            if cacheable:
                self._refs[page] = 0
                self._cached[page] = None
            else:
                del self._refs[page]
                self._free.append(page)

    def drop_cached(self, page: int) -> None:
        """Forget a cached (refcount-0) page, returning it to the free
        list. Notifies ``on_evict`` like pressure eviction does, so the
        prefix cache drops the hashes pointing at it — a silently freed
        page whose hash survived would hand its next owner's content to
        strangers."""
        if page in self._cached:
            del self._cached[page]
            del self._refs[page]
            if self.on_evict is not None:
                self.on_evict(page)
            self._free.append(page)

    def _evict_one(self) -> None:
        page = next(iter(self._cached))  # oldest
        del self._cached[page]
        del self._refs[page]
        if self.on_evict is not None:
            self.on_evict(page)
        self._free.append(page)


@dataclasses.dataclass
class Sequence:
    """One request's generation state (host side)."""

    rid: str
    prompt_ids: List[int]
    params: SamplingParams
    output_ids: List[int] = dataclasses.field(default_factory=list)
    pages: List[int] = dataclasses.field(default_factory=list)
    # SLO class ("interactive" | "batch"). Under a priority-aware
    # scheduler interactive sequences are admitted first and may
    # preempt batch victims; it never changes a sequence's own token
    # stream (greedy parity with priority off holds per request).
    priority: str = "batch"
    # Prefix caching: leading prompt positions whose KV is already in the
    # (shared) leading pages — prefill starts at prefix_len. cacheable_pages
    # counts the leading pages registered in the prefix cache (they park
    # in the evictable pool instead of the free list when released).
    prefix_len: int = 0
    cacheable_pages: int = 0
    # Bumped on every preemption: in-flight device results snapshotted
    # under an older epoch must not be appended after re-admission.
    epoch: int = 0
    slot: int = -1
    admitted_at: int = -1  # scheduler tick of (last) admission, for LIFO preempt
    preempt_count: int = 0
    prefilled: bool = False  # KV cache holds this sequence (engine sets it)
    # Disaggregated prefill role: stop after the prompt phase — the first
    # sampled token is discarded, the prompt KV is snapshotted, and the
    # sequence finishes with finish_reason="prefill_done" so the worker
    # hands it to the decode pool (which re-samples that token from the
    # same key chain, bit-identically).
    prefill_only: bool = False
    # Wall-clock (time.time()) deadline, or None. The engine's sweep
    # expires waiting/running sequences past it between decode steps with
    # finish_reason="deadline_exceeded"; the worker dead-letters those.
    deadline_at: Optional[float] = None
    finish_reason: Optional[str] = None
    finish_text: Optional[str] = None  # pre-truncated text on stop-string hit
    # Incremental detokenization cache (engine-owned, stop-string
    # requests only): ``detok_text`` is the decoded text of
    # ``output_ids[:detok_len]``. The engine keeps the cached head a
    # safe token margin behind the end, so per-token stop-string checks
    # decode only the short tail instead of re-decoding the output.
    # Survives preemption (output_ids are kept, so the prefix decode is
    # still valid); the engine resets it whenever output_ids are
    # truncated past detok_len.
    detok_len: int = 0
    detok_text: str = ""
    # Host-held KV pages awaiting re-insertion (a snapshot.KVRestore).
    # Set by swap-to-host preemption and by insert_request; consumed at
    # admission — the engine scatters the pages back instead of
    # re-prefilling. None = re-prefill from prompt+output as usual.
    restore: Optional[Any] = None
    # Host-tier prefix promotion: [(page, chain_hash, PrefixEntry), ...]
    # assigned at admission when the host prefix store extends the
    # device-cache match. The engine inserts the entries' KV into the
    # listed pages before this sequence's first dispatch and clears the
    # field; prefix_len already counts these positions.
    host_restore: Optional[List[Any]] = None
    # Host-side lifecycle stamps (time.monotonic(); 0.0 = not yet).
    # These feed the queue-wait / TTFT / ITL histograms and the
    # per-request trace record; they never influence scheduling.
    t_enqueue: float = 0.0
    t_admit: float = 0.0
    t_prefill_start: float = 0.0
    t_first_token: float = 0.0
    t_last_token: float = 0.0
    t_preempt: float = 0.0

    @property
    def num_tokens(self) -> int:
        return len(self.prompt_ids) + len(self.output_ids)

    @property
    def last_token(self) -> int:
        return self.output_ids[-1] if self.output_ids else self.prompt_ids[-1]


@dataclasses.dataclass
class SchedulerConfig:
    max_num_seqs: int
    num_pages: int
    page_size: int
    max_model_len: int
    # Automatic prefix caching: sequences sharing full leading prompt
    # pages (position-identical, so RoPE'd K matches) reuse them via
    # refcounts instead of recomputing — the engine then prefills only
    # from prefix_len on (requires chunked prefill).
    enable_prefix_caching: bool = False
    # SLO-aware admission: interactive waiters are admitted before batch
    # waiters (FIFO within each class). Off (default) = pure FIFO, the
    # exact pre-priority order, and stats() omits the per-class keys so
    # default payloads stay byte-identical.
    priority_aware: bool = False

    @property
    def pages_per_seq(self) -> int:
        return -(-self.max_model_len // self.page_size)  # ceil


class Scheduler:
    """Slot/page bookkeeping for the continuous batch."""

    def __init__(self, config: SchedulerConfig) -> None:
        self.config = config
        self.allocator = PageAllocator(config.num_pages)
        self.slots: List[Optional[Sequence]] = [None] * config.max_num_seqs
        self.waiting: Deque[Sequence] = deque()
        self.running: Dict[str, Sequence] = {}
        self._tick = 0
        # Prefix cache: chain-hash of the prompt's leading full pages →
        # page id holding that KV, plus the reverse map for eviction.
        self._prefix_cache: Dict[bytes, int] = {}
        self._prefix_rev: Dict[int, List[bytes]] = {}
        self.prefix_hits = 0  # pages reused via the cache (stats)
        self.prefix_misses = 0  # full prompt pages that had to prefill
        self.preemptions = 0  # recompute preemptions (stats)
        # Called as on_preempt(seq, defer_pages) at the top of preempt(),
        # before the epoch bump and page release (engine swap-to-host).
        self.on_preempt = None
        # Host prefix tier hooks (engine-owned; both optional):
        #   on_demote(page, hashes) — fires when a cache-registered page
        #     is evicted, while its device content is still intact, with
        #     the chain hashes that pointed at it (park the KV in host
        #     RAM instead of losing it);
        #   host_lookup(hashes) — returns the longest contiguous
        #     [(hash, entry), ...] run the host tier holds for a chain
        #     tail the device cache missed.
        self.on_demote = None
        self.host_lookup = None
        self._suppress_demote = False  # invalidation must not demote
        self.allocator.on_evict = self._drop_page_hashes
        # Per-scheduler latency histograms (the owning engine registers
        # them into the process-wide registry for /metrics export).
        self.queue_wait_hist = Histogram(
            "llmq_queue_wait_seconds",
            "Enqueue-to-first-admission wait per request",
        )
        self.preempt_delay_hist = Histogram(
            "llmq_preemption_delay_seconds",
            "Preemption-to-readmission delay per recompute preemption",
        )

    # --- prefix caching ---------------------------------------------------
    def _prefix_hashes(self, prompt_ids: List[int]) -> List[bytes]:
        """Chain digests of the prompt's leading FULL pages
        (utils/hashing.py: the fleet-wide KV page identity — the host
        prefix store and cross-worker shipping key on the same bytes)."""
        return token_prefix_chain(prompt_ids, self.config.page_size)

    def _match_prefix(self, prompt_ids: List[int]) -> List[int]:
        """Longest run of cached pages matching the prompt's hash chain."""
        return self._match_prefix_hashes(self._prefix_hashes(prompt_ids))

    def _match_prefix_hashes(self, hashes: List[bytes]) -> List[int]:
        matched: List[int] = []
        for h in hashes:
            page = self._prefix_cache.get(h)
            if page is None:
                break
            matched.append(page)
        return matched

    def register_prefix(self, seq: Sequence) -> None:
        """Offer a prefilled sequence's full prompt pages to the cache.
        First writer wins per hash; only the leading pages that ARE the
        cache's pages count as cacheable on release (a losing page would
        park in the evictable pool with no hash pointing at it)."""
        if not self.config.enable_prefix_caching:
            return
        cacheable = 0
        for i, h in enumerate(self._prefix_hashes(seq.prompt_ids)):
            if i >= len(seq.pages):
                break
            page = self._prefix_cache.get(h)
            if page is None:
                self._prefix_cache[h] = seq.pages[i]
                self._prefix_rev.setdefault(seq.pages[i], []).append(h)
                cacheable = i + 1
            elif page == seq.pages[i]:
                cacheable = i + 1  # re-admission re-matched the same page
            else:
                break  # a different page already serves this chain
        seq.cacheable_pages = cacheable

    def _drop_page_hashes(self, page: int) -> None:
        hashes = [
            h
            for h in self._prefix_rev.pop(page, [])
            if self._prefix_cache.get(h) == page
        ]
        for h in hashes:
            del self._prefix_cache[h]
        # Demote to the host tier while the page's device content is
        # still intact (on_evict fires before the page hits the free
        # list) — unless invalidation is in flight, in which case the
        # content is exactly what must NOT survive.
        if hashes and self.on_demote is not None and not self._suppress_demote:
            self.on_demote(page, hashes)

    def invalidate_prefix_cache(self) -> None:
        """Forget every cached prefix and return the parked pages to the
        free list — required when the engine rebuilds the KV buffers
        (after a failed step): the page ids would otherwise still match
        hash chains while pointing at zeroed content. Demotion is
        suppressed throughout — parking a page from an aborted/zeroed
        buffer would re-serve poisoned KV from host RAM later."""
        self._suppress_demote = True
        try:
            for page in list(self.allocator._cached):
                self.allocator.drop_cached(page)
        finally:
            self._suppress_demote = False
        self._prefix_cache.clear()
        self._prefix_rev.clear()
        for seq in list(self.running.values()) + list(self.waiting):
            seq.cacheable_pages = 0  # nothing may re-park as cached

    # --- queue ------------------------------------------------------------
    def add(self, seq: Sequence) -> None:
        # Overlong prompts are truncated to fit the context window, and
        # generation is capped so prompt+output never exceeds max_model_len
        # (vLLM max_model_len parity); finish_reason=length surfaces it.
        limit = self.config.max_model_len - 1
        if len(seq.prompt_ids) > limit:
            seq.prompt_ids = seq.prompt_ids[:limit]
        if seq.num_tokens + seq.params.max_tokens > self.config.max_model_len:
            seq.params.max_tokens = max(
                0, self.config.max_model_len - seq.num_tokens
            )
        if self._pages_needed(seq.num_tokens) > self.config.num_pages - 1:
            # Even an empty pool could never hold the prompt: reject now —
            # otherwise admit() retries forever and the engine livelocks.
            raise ValueError(
                f"prompt of {seq.num_tokens} tokens needs "
                f"{self._pages_needed(seq.num_tokens)} KV pages; pool has "
                f"{self.config.num_pages - 1}"
            )
        if seq.t_enqueue == 0.0:
            seq.t_enqueue = time.monotonic()
        self.waiting.append(seq)

    def add_restored(self, seq: Sequence) -> None:
        """Enqueue a snapshot-restored sequence.

        Unlike :meth:`add`, the prompt is never truncated — the snapshot's
        KV and key chain cover exactly these positions, so silently
        shortening them would desynchronize state — and the generation cap
        is re-derived from the PROMPT length alone. Running the restored
        sequence through add()'s cap (which counts ``num_tokens``, i.e.
        prompt PLUS already-generated output) would tighten ``max_tokens``
        below what the source engine granted and could instantly
        length-finish a request that still had budget.
        """
        if seq.num_tokens >= self.config.max_model_len:
            raise ValueError(
                f"restored request {seq.rid!r} holds {seq.num_tokens} "
                f"tokens; this engine's window is {self.config.max_model_len}"
            )
        window = self.config.max_model_len - len(seq.prompt_ids)
        if seq.params.max_tokens > window:
            seq.params.max_tokens = window
        if self._pages_needed(seq.num_tokens) > self.config.num_pages - 1:
            raise ValueError(
                f"restored request {seq.rid!r} of {seq.num_tokens} tokens "
                f"needs {self._pages_needed(seq.num_tokens)} KV pages; pool "
                f"has {self.config.num_pages - 1}"
            )
        if seq.t_enqueue == 0.0:
            seq.t_enqueue = time.monotonic()
        self.waiting.append(seq)

    @property
    def has_waiting(self) -> bool:
        return bool(self.waiting)

    @property
    def num_running(self) -> int:
        return len(self.running)

    def _pages_needed(self, num_tokens: int) -> int:
        # +1 position of headroom: the decode step writes the *next* token's
        # KV before the host learns the sequence finished.
        return -(-(num_tokens + 1) // self.config.page_size)

    # --- admission --------------------------------------------------------
    def _next_admit_index(self) -> int:
        """Index of the next waiting sequence to admit: FIFO head, unless
        the scheduler is priority-aware and an interactive sequence waits
        anywhere in the queue — then the OLDEST interactive waiter jumps
        the line (FIFO within each class; a preempted interactive
        sequence sits at the head already via appendleft)."""
        if self.config.priority_aware:
            for i, seq in enumerate(self.waiting):
                if seq.priority == "interactive":
                    return i
        return 0

    def admit(self, max_new: Optional[int] = None) -> List[Sequence]:
        """Move waiting sequences into free slots while pages allow.

        Returns the newly admitted sequences (their ``slot`` and ``pages``
        set); each needs a prefill pass before joining decode. Admission
        is FIFO; a priority-aware scheduler admits interactive waiters
        first (see ``_next_admit_index``).
        """
        admitted: List[Sequence] = []
        free_slots = [i for i, s in enumerate(self.slots) if s is None]
        while self.waiting and free_slots:
            if max_new is not None and len(admitted) >= max_new:
                break
            idx = self._next_admit_index()
            seq = self.waiting[idx]
            matched: List[int] = []
            host: List[Any] = []
            hashes: List[bytes] = []
            if self.config.enable_prefix_caching:
                hashes = self._prefix_hashes(seq.prompt_ids)
                matched = self._match_prefix_hashes(hashes)
                # Share FIRST: matched refcount-0 pages leave the
                # evictable pool, so the fresh alloc below cannot evict
                # them out from under us.
                for page in matched:
                    self.allocator.share(page)
                # Extend the device match from the host tier (snapshot
                # restores bring their own KV — don't double-restore).
                if self.host_lookup is not None and seq.restore is None:
                    host = self.host_lookup(hashes[len(matched) :])
            need = self._pages_needed(seq.num_tokens) - len(matched)
            try:
                fresh = self.allocator.alloc(need) if need > 0 else []
            except OutOfPages:
                for page in matched:  # undo the shares; stay cacheable
                    self.allocator.free([page], cacheable=True)
                break
            seq.pages = matched + fresh
            if host:
                # Promoted pages come out of the fresh allocation (the
                # chain always has at least one more page than its full
                # prefix pages, so fresh covers them). Register their
                # hashes NOW: the engine inserts the host KV before this
                # sequence's first dispatch, so later admits may share.
                promoted = fresh[: len(host)]
                seq.host_restore = [
                    (page, h, entry)
                    for page, (h, entry) in zip(promoted, host)
                ]
                for page, h, _ in seq.host_restore:
                    self._prefix_cache[h] = page
                    self._prefix_rev.setdefault(page, []).append(h)
            n_reused = len(matched) + len(host)
            seq.prefix_len = n_reused * self.config.page_size
            # Matched pages are cache-registered by construction; they
            # must park back in the evictable pool on release even if
            # this sequence never re-registers (e.g. finishes early).
            seq.cacheable_pages = n_reused
            self.prefix_hits += n_reused
            self.prefix_misses += len(hashes) - n_reused
            del self.waiting[idx]
            seq.slot = free_slots.pop(0)
            seq.admitted_at = self._tick
            self._tick += 1
            now = time.monotonic()
            if seq.t_preempt > 0.0:  # re-admission after a preemption
                self.preempt_delay_hist.observe(now - seq.t_preempt)
                seq.t_preempt = 0.0
            elif seq.t_enqueue > 0.0 and seq.t_admit == 0.0:
                self.queue_wait_hist.observe(now - seq.t_enqueue)
            seq.t_admit = now
            self.slots[seq.slot] = seq
            self.running[seq.rid] = seq
            admitted.append(seq)
        return admitted

    # --- decode-step bookkeeping -----------------------------------------
    def append_token(self, seq: Sequence, token: int) -> None:
        """Record a generated token, growing the page map as it crosses a
        page boundary. May preempt *other* sequences to find a page; raises
        OutOfPages only if even preemption can't help (seq is last alive)."""
        seq.output_ids.append(token)
        self.ensure_pages(seq, seq.num_tokens + 1)

    def ensure_pages(
        self,
        seq: Sequence,
        num_positions: int,
        *,
        allow_preempt: bool = True,
        preemptible=None,
    ) -> None:
        """Grow ``seq``'s page map to cover ``num_positions`` KV slots
        (capped at the per-sequence maximum). The engine's run-ahead
        pipeline calls this *at dispatch time* with a lookahead, so pages
        always exist on-device before the step that writes them — with
        fused decode blocks the lookahead is measured in blocks of
        ``decode_block`` positions (every in-flight dispatch may write K
        KV rows per sequence before the host sees any of its tokens), so
        each block's full K positions are pre-reserved here. Speculative
        decoding multiplies that per-iteration demand by spec_tokens+1:
        a verify step writes KV for EVERY candidate position whether or
        not it is accepted (rejected writes are simply overwritten
        later), so the engine's lookahead covers
        ``(pending + 1) * (decode_block * (spec_tokens + 1)) + 1``
        positions; preemption and epoch semantics are unchanged. May
        preempt other sequences (unless ``allow_preempt`` is off — the
        engine forbids it while steps are in flight, because a victim's
        freed pages could still be written); ``preemptible`` optionally
        filters victims (the engine excludes mid-prefill sequences, whose
        in-flight chunk loop would keep writing into freed pages); raises
        OutOfPages otherwise."""
        cap = self.config.pages_per_seq * self.config.page_size
        num_positions = min(num_positions, cap)
        while -(-num_positions // self.config.page_size) > len(seq.pages):
            try:
                seq.pages.extend(self.allocator.alloc(1))
            except OutOfPages:
                if not allow_preempt:
                    raise
                victim = self._youngest_running(
                    exclude=seq.rid, preemptible=preemptible
                )
                if victim is None:
                    raise
                self.preempt(victim)

    def _youngest_running(
        self, exclude: str, preemptible=None
    ) -> Optional[Sequence]:
        candidates = [
            s
            for s in self.running.values()
            if s.rid != exclude and (preemptible is None or preemptible(s))
        ]
        if not candidates:
            return None
        if self.config.priority_aware:
            # Page pressure evicts batch work before interactive work:
            # an interactive victim pays its whole SLO back in re-prefill.
            batch = [s for s in candidates if s.priority != "interactive"]
            if batch:
                candidates = batch
        return max(candidates, key=lambda s: s.admitted_at)

    def preempt(
        self, seq: Sequence, *, defer_pages: bool = False
    ) -> Tuple[List[int], int]:
        """Evict a running sequence back to the waiting queue (head, so it
        resumes first). Its generated tokens are kept; re-admission
        re-prefills prompt+generated to rebuild the KV cache. With
        ``defer_pages`` (self-preemption while steps are in flight) the
        pages are detached and returned like ``finish(defer_pages=True)``
        instead of freed — the engine releases them at the watermark."""
        # Engine hook (swap-to-host preemption): fires while the victim
        # still holds its pages and its prefilled flag — for an immediate
        # (non-deferred, pipeline-drained) preemption the engine gathers
        # the KV to host right here, before the pages hit the free list.
        if self.on_preempt is not None:
            self.on_preempt(seq, defer_pages)
        seq.epoch += 1  # stale in-flight results must not resurface
        pages, cacheable = [], 0
        if defer_pages:
            pages = seq.pages
            cacheable = min(seq.cacheable_pages, len(pages))
            seq.pages = []
            seq.cacheable_pages = 0
        self._release(seq)
        seq.preempt_count += 1
        seq.t_preempt = time.monotonic()
        self.preemptions += 1
        seq.prefilled = False  # KV is gone; re-admission re-prefills
        self.waiting.appendleft(seq)
        return pages, cacheable

    def finish(
        self, seq: Sequence, reason: str, *, defer_pages: bool = False
    ) -> Tuple[List[int], int]:
        """Finish a sequence. With ``defer_pages`` the slot is released
        but the KV pages are detached and *returned* (with the count of
        leading cache-registered pages) instead of freed — the engine
        holds them until every in-flight device step that may still write
        them has completed, then calls ``release_pages``."""
        seq.finish_reason = reason
        pages, cacheable = [], 0
        if defer_pages:
            pages = seq.pages
            cacheable = min(seq.cacheable_pages, len(pages))
            seq.pages = []
        self._release(seq)
        return pages, cacheable

    def release_pages(self, pages: List[int], cacheable: int = 0) -> None:
        """Return deferred pages (from ``finish(defer_pages=True)``); the
        leading ``cacheable`` pages park in the evictable prefix pool."""
        if cacheable:
            self.allocator.free(pages[:cacheable], cacheable=True)
        if pages[cacheable:]:
            self.allocator.free(pages[cacheable:])

    def _release(self, seq: Sequence) -> None:
        if seq.slot >= 0:
            self.slots[seq.slot] = None
            seq.slot = -1
        self.running.pop(seq.rid, None)
        if seq.pages:
            self.release_pages(
                seq.pages, min(seq.cacheable_pages, len(seq.pages))
            )
            seq.pages = []

    # --- introspection ----------------------------------------------------
    def stats(self) -> Dict[str, float]:
        total_pages = self.config.num_pages - 1
        out = {
            "running": len(self.running),
            "waiting": len(self.waiting),
            "slots": self.config.max_num_seqs,
            "batch_occupancy": len(self.running) / self.config.max_num_seqs,
            "kv_page_utilization": (total_pages - self.allocator.available)
            / max(1, total_pages),
            "preemptions": self.preemptions,
        }
        if self.config.priority_aware:
            out["waiting_interactive"] = sum(
                1 for s in self.waiting if s.priority == "interactive"
            )
            out["running_interactive"] = sum(
                1
                for s in self.running.values()
                if s.priority == "interactive"
            )
        qw = self.queue_wait_hist
        pd = self.preempt_delay_hist
        out["queue_wait_p50_ms"] = _ms(qw.percentile(0.50))
        out["queue_wait_p95_ms"] = _ms(qw.percentile(0.95))
        out["preemption_delay_p50_ms"] = _ms(pd.percentile(0.50))
        if self.config.enable_prefix_caching:
            out["prefix_cache_hit_pages"] = self.prefix_hits
            out["prefix_cache_miss_pages"] = self.prefix_misses
            seen = self.prefix_hits + self.prefix_misses
            out["prefix_hit_rate"] = (
                self.prefix_hits / seen if seen else 0.0
            )
        return out

    def check_invariants(self) -> None:
        """Debug/test hook: assert the documented invariants."""
        counts: Dict[int, int] = {}
        for seq in self.running.values():
            assert self.slots[seq.slot] is seq
            assert self._pages_needed(seq.num_tokens) <= len(seq.pages)
            for page in seq.pages:
                counts[page] = counts.get(page, 0) + 1
        assert 0 not in counts, "scratch page handed out"
        for page, n in counts.items():
            rc = self.allocator.refcount(page)
            assert rc == n, f"page {page}: refcount {rc} != {n} owners"
        if not self.config.enable_prefix_caching:
            assert all(n == 1 for n in counts.values()), "page owned twice"
        assert (
            len(counts) + self.allocator.available
            == self.config.num_pages - 1
        )
