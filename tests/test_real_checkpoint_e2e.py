"""End-to-end with a genuine HF checkpoint directory (no network).

VERDICT r1 weak #7: everything end-to-end used ``preset://`` random
weights + ByteTokenizer; the HFTokenizer + load_checkpoint + chat
template path had no coverage. This test drives the full
submit→broker→TPUWorker→receive stack against a checkpoint directory
that is layout-identical to a hub download (sharded safetensors +
model.safetensors.index.json + tokenizer.json + chat template), built
offline by ``tests/make_hf_fixture.py`` — the same code path a real
Qwen2.5 checkpoint takes (reference: vllm_worker.py:103-195).
"""

import asyncio
import uuid

import pytest

pytest.importorskip("torch")
pytest.importorskip("transformers")
pytest.importorskip("tokenizers")

from llmq_tpu.broker.manager import BrokerManager  # noqa: E402
from llmq_tpu.core.models import Job, Result  # noqa: E402


@pytest.fixture(scope="module")
def hf_checkpoint(tmp_path_factory):
    from tests.make_hf_fixture import build

    return build(tmp_path_factory.mktemp("hf") / "qwen2-micro")


@pytest.mark.slow
def test_hf_checkpoint_full_stack(hf_checkpoint, monkeypatch):
    from llmq_tpu.workers.tpu_worker import TPUWorker

    url = f"memory://hf-{uuid.uuid4().hex[:8]}"
    monkeypatch.setenv("LLMQ_BROKER_URL", url)

    async def main():
        worker = TPUWorker(
            "hfq",
            model=str(hf_checkpoint),
            max_model_len=256,
            max_num_seqs=4,
            num_pages=64,
            page_size=8,
        )
        task = asyncio.create_task(worker.run())
        await asyncio.sleep(0.1)
        mgr = BrokerManager(url=url)
        await mgr.connect()
        await mgr.setup_queue_infrastructure("hfq")
        await mgr.publish_job(
            "hfq",
            Job(
                id="chat1",
                messages=[{"role": "user", "content": "Say hello."}],
                max_tokens=8,
            ),
        )
        await mgr.publish_job(
            "hfq",
            Job(id="plain1", prompt="The quick brown", max_tokens=8,
                temperature=0.0),
        )
        got = {}

        async def on_result(msg):
            r = Result.model_validate_json(msg.body)
            got[r.id] = r
            await msg.ack()

        await mgr.consume_results("hfq", on_result)
        for _ in range(1200):
            if len(got) >= 2:
                break
            await asyncio.sleep(0.25)
        worker.request_shutdown()
        await asyncio.wait_for(task, timeout=60)
        await mgr.disconnect()
        return got

    got = asyncio.run(main())
    assert set(got) == {"chat1", "plain1"}
    chat = got["chat1"]
    # The chat template wraps the message in <|im_start|>/<|im_end|>
    # markers + generation prompt, so the tokenized prompt must be well
    # above the bare 3-4 word content.
    assert chat.usage["prompt_tokens"] > 10
    assert chat.usage["completion_tokens"] == 8
    assert isinstance(chat.result, str)
    plain = got["plain1"]
    assert plain.usage["prompt_tokens"] <= 6
    assert plain.usage["completion_tokens"] == 8
