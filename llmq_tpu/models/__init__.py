"""Model definitions: one generic decoder covering the Llama/Qwen2/Gemma2
families (the model set the reference's production runs used — Tower-Plus is
Qwen2-based, plus Llama-3.2 and Gemma-2 from BASELINE.json configs).
"""

from llmq_tpu.models.config import ModelConfig
from llmq_tpu.models.quant import quantize_params
from llmq_tpu.models.transformer import Transformer, init_params

__all__ = ["ModelConfig", "Transformer", "init_params", "quantize_params"]
