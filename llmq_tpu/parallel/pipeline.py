"""Pipeline-stage planning: carve a ``(pp, dp, sp, tp)`` mesh into
per-stage compute submeshes and slice the stacked param tree per stage.

GPipe-style inference pipelining (Pope et al. 2022's inter-stage bubble
analysis): the layer stack splits into ``pp`` contiguous stages, each
compiled as its own executable over one 3-axis ICI submesh. Nothing is
ever sharded over the ``pp`` axis — stage-to-stage activations move by
explicit host-driven transfer (device-to-device over ICI in one
process, the snapshot-codec wire frame over ``tcp://`` between hosts),
so GSPMD sees ``pp`` nowhere and the spmd gate can assert that no
collective crosses a stage boundary.

The helpers here are deliberately engine-agnostic (pure functions over
meshes and pytrees) so the spmd gate, the bench rung, and the probes
can reuse them without constructing an engine.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from llmq_tpu.parallel.mesh import INNER_AXIS_NAMES, PP_AXIS

Params = Dict[str, Any]


def stage_layer_ranges(num_layers: int, pp: int) -> List[Tuple[int, int]]:
    """Contiguous ``[lo, hi)`` layer ranges for ``pp`` stages.

    Near-even split; when layers don't divide evenly the EARLIER stages
    take the extra layer — the last stage also owns the final norm +
    lm_head matmul (the [H, V] matmul is the single biggest non-layer
    cost), so biasing remainders forward balances wall-clock per stage.
    """
    if pp < 1:
        raise ValueError(f"pp={pp} must be >= 1")
    if num_layers < pp:
        raise ValueError(
            f"cannot split {num_layers} layers into {pp} pipeline stages"
        )
    base, extra = divmod(num_layers, pp)
    ranges: List[Tuple[int, int]] = []
    lo = 0
    for s in range(pp):
        hi = lo + base + (1 if s < extra else 0)
        ranges.append((lo, hi))
        lo = hi
    assert lo == num_layers
    return ranges


def stage_submeshes(mesh: Mesh) -> List[Mesh]:
    """One 3-axis ``(dp, sp, tp)`` Mesh per pp slice of a 4-axis mesh.

    Each submesh is a contiguous device block (one ICI domain in the
    two-tier deployment shape); inner shardings carry over unchanged
    because the axis names and extents match the classic single-stage
    mesh exactly.
    """
    if PP_AXIS not in mesh.axis_names:
        return [mesh]
    pp_index = mesh.axis_names.index(PP_AXIS)
    if pp_index != 0:
        raise ValueError(
            f"pp must be the outermost mesh axis, got {mesh.axis_names}"
        )
    grid = np.asarray(mesh.devices)
    return [Mesh(grid[s], INNER_AXIS_NAMES) for s in range(grid.shape[0])]


def slice_stage_params(
    params: Params,
    lo: int,
    hi: int,
    *,
    num_layers: int,
    tied_embeddings: bool,
) -> Params:
    """The param subtree stage ``[lo, hi)`` of ``num_layers`` executes.

    Layer-stacked leaves slice on their leading [L, ...] axis (nested
    quant {q, scale} dicts slice leaf-wise for free via tree.map); the
    non-layer leaves place by role: ``embed`` on the first stage (token
    lookup) AND on the last when embeddings are tied (the lm_head
    matmul reads it), ``final_norm``/``lm_head`` on the last stage only.
    Duplicating the tied embed across two stages costs one [V, H] copy
    of HBM — the price of not shipping hidden states back to stage 0
    for every logits computation.
    """
    first = lo == 0
    last = hi == num_layers
    out: Params = {
        "layers": jax.tree.map(lambda x: x[lo:hi], params["layers"])
    }
    if first or (last and tied_embeddings) or (last and "lm_head" not in params):
        out["embed"] = params["embed"]
    if last:
        out["final_norm"] = params["final_norm"]
        if "lm_head" in params:
            out["lm_head"] = params["lm_head"]
    return out


def bubble_fraction(microbatches: int, stages: int) -> float:
    """GPipe bubble fraction ``(pp - 1) / (m + pp - 1)``.

    ``m`` microbatches through ``pp`` stages take ``m + pp - 1`` stage
    slots of which ``pp - 1`` are fill/drain bubbles (Pope et al. 2022,
    §3.3). For decode the run-ahead pipeline plays the role of ``m``:
    K-deep dispatch (``decode_block`` iterations per dispatch, runahead
    dispatches in flight) amortizes the same way.
    """
    m = max(1, int(microbatches))
    pp = max(1, int(stages))
    return (pp - 1) / (m + pp - 1)


def boundary_bytes_per_token(hidden_size: int, itemsize: int = 4) -> int:
    """Activation bytes one token's hidden state ships per stage
    boundary (the DCN-vs-ICI planning number: [H] * itemsize)."""
    return int(hidden_size) * int(itemsize)
